"""Deterministic fault injection for the resilience layer.

The tunnel's real failure modes — transient TPU worker death (a
pagerank-mp sample collapsed 10x in BENCH_r05 and one whole config
crashed during round 5), slow segments, and state corruption — do not
reproduce on demand, so the recovery paths that handle them would
otherwise ship untested.  This module injects synthetic versions of
those failures at SEGMENT BOUNDARIES on a deterministic schedule
(explicit, or derived from a seed), so the whole
classify/retry/resume path (lux_tpu/resilience.py) is exercised by
the CPU test suite.

Round 9 adds the data-plane corruption classes: type-appropriate
state corruption (NaN for float states, the program's
identity/sentinel for integer labels — all four apps are
corruption-testable) and on-disk checkpoint corruption (a zip-valid
bit flip only the per-leaf CRC can catch, and a truncation), each
followed by an injected crash so checkpoint.py's generation-fallback
resume path is exercised end-to-end by the CPU suite.

Round 11 adds the TOPOLOGY fault classes: DEVICE_LOSS (named mesh
devices become unavailable) and WORKER_KILL (a whole worker process
dies — simulated in-process via a typed raise, or REAL via
``hard_kill`` + os._exit for the multi-process heartbeat harness), so
the elastic degraded-mesh recovery path (resilience.supervised_run's
``elastic=``, lux_tpu/heartbeat.py) is deterministically exercised on
the 8-virtual-device CPU mesh and in the 2-subprocess harness.

Faults key on a global boundary COUNTER, not on iteration numbers:
after a crash-and-resume the counter has advanced past the fired
fault, so a schedule never re-fires and every supervised run
terminates.  The counter also persists across the supervisor's
retries, which is what makes a seeded schedule reproducible
end-to-end.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

CRASH = "crash"     # raise InjectedWorkerCrash (retryable)
DELAY = "delay"     # sleep delay_s (exercises slow-segment paths)
NAN = "nan"         # corrupt the first float leaf (NaN) — or, for
#                     integer-labeled programs, poke the program's
#                     identity/sentinel value (corrupt_state)
CKPT_BITFLIP = "ckpt_bitflip"    # flip a payload bit in the newest
#                                  checkpoint generation, then crash
CKPT_TRUNCATE = "ckpt_truncate"  # truncate the newest checkpoint
#                                  generation, then crash
DEVICE_LOSS = "device_loss"      # raise InjectedDeviceLoss naming the
#                                  mesh devices that "died" (TOPOLOGY
#                                  class: the elastic supervisor
#                                  shrinks the mesh over the survivors)
WORKER_KILL = "worker_kill"      # raise InjectedWorkerKill (a whole
#                                  worker process gone, its devices
#                                  with it) — or, with hard_kill=True,
#                                  REALLY kill this process (the
#                                  2-subprocess harness's genuine
#                                  death, detected by the peers'
#                                  heartbeat deadline)

# round 20 (live graphs, lux_tpu/livegraph.py): mutation-scoped
# actions for the crash-consistent mutation log — each exercises one
# leg of the WAL/compaction recovery contract
MUT_CRASH = "mut_crash"          # crash BEFORE the WAL append lands:
#                                  the mutation was never durable, so
#                                  replay must not show it
WAL_TORN = "wal_torn"            # crash MID-append: only a PREFIX of
#                                  the record's bytes reach disk — the
#                                  torn tail replay must detect (CRC
#                                  chain), truncate, and never replay
COMPACT_CRASH = "compact_crash"  # crash between COMPACT_START and the
#                                  atomic generation swap: recovery
#                                  resumes from the SURVIVING
#                                  generation (base + published delta)

# round 21 (mutation algebra): op-ASSERTING crash legs.  Each behaves
# like MUT_CRASH (die before the WAL record lands) but additionally
# validates that the mutation firing at that index really is the
# scheduled op — a drill that says "kill the 3rd mutation, which is a
# deletion" fails typed if the stream reordered, instead of silently
# testing the wrong op's recovery leg.
MUT_DELETE = "mut_delete"        # crash before a DELETE record lands
MUT_REWEIGHT = "mut_reweight"    # crash before a REWEIGHT record lands
RESEED_CRASH = "reseed_crash"    # crash MID-RE-SEED: between the
#                                  affected-cone computation and the
#                                  re-converge — recovery must come up
#                                  with the anti-monotone ops still
#                                  pending (admission stays capped; no
#                                  answer was produced from the
#                                  half-re-seeded state)

# round 24 (self-healing fleet, lux_tpu/fleet.py + journal.py): the
# whole-fleet and flapping-replica classes the resurrection /
# recovery paths must survive
FLEET_CRASH = "fleet_crash"      # the ENTIRE fleet dies at the named
#                                  replica's Nth boundary, coordinator
#                                  included (in-process: a typed
#                                  InjectedFleetCrash that propagates
#                                  out of FleetServer.run; hard_kill:
#                                  os._exit) — recovery restarts from
#                                  the admission journal + mutation WAL
REPLICA_FLAP = "replica_flap"    # kill the SAME replica at every
#                                  boundary from the scheduled index on
#                                  (re-fires, unlike every other plan
#                                  action): each resurrection dies
#                                  again until flap detection trips the
#                                  typed quarantine (fleet.py)


# exit code of a hard_kill WORKER_KILL: distinguishable from a crash
# (nonzero, outside the shell/signal ranges) in the harness's asserts
HARD_KILL_CODE = 113


class InjectedWorkerCrash(RuntimeError):
    """Synthetic analogue of the tunnel's transient worker death;
    resilience.classify treats it as retryable."""


class InjectedDeviceLoss(RuntimeError):
    """Synthetic topology fault: named devices of the engine's mesh
    became unavailable.  Carries ``lost_devices`` (device ids);
    resilience.classify treats it as TOPOLOGY — retrying on the same
    mesh cannot help, but re-placement onto the survivors can."""

    def __init__(self, msg: str, lost_devices=()):
        super().__init__(msg)
        self.lost_devices = tuple(int(d) for d in lost_devices)


class InjectedWorkerKill(RuntimeError):
    """Synthetic topology fault: a whole worker process died, taking
    its devices with it (the message mimics the coordination-service
    heartbeat signature real deaths surface as).  Carries
    ``lost_devices`` like InjectedDeviceLoss; classified TOPOLOGY."""

    def __init__(self, msg: str, lost_devices=()):
        super().__init__(msg)
        self.lost_devices = tuple(int(d) for d in lost_devices)


class InjectedFleetCrash(BaseException):
    """Synthetic whole-fleet death (round 24): the coordinator AND
    every replica die at once — nothing survives to fail over to, so
    this is NOT retryable within the process and deliberately
    subclasses BaseException: no except-Exception recovery path in
    the dispatcher may swallow it (a real power loss is not
    swallowed either).  The only legitimate continuation is
    ``FleetServer.recover`` over the durable state (admission
    journal + mutation WAL + checkpoints).  Carries ``replica`` —
    the replica whose boundary the crash fired at."""

    def __init__(self, msg: str, replica: str = ""):
        super().__init__(msg)
        self.replica = replica


@dataclasses.dataclass
class FaultPlan:
    """A deterministic boundary-counter -> action schedule.

    ``fire(state)`` is called by the supervisor at every segment
    boundary.  It returns None (no state change), or a HOST-side
    corrupted copy of the state pytree (the caller re-places it on
    device); a scheduled CRASH raises InjectedWorkerCrash before the
    segment's checkpoint save; a scheduled DELAY sleeps.  ``fired``
    records what actually happened, for assertions.
    """

    schedule: dict
    delay_s: float = 0.0
    nan_count: int = 1
    # sentinel poked into integer-labeled states by a NAN action (the
    # supervisor passes the program identity per-call; this is the
    # standalone-use default)
    int_value: int | None = None
    # devices a DEVICE_LOSS/WORKER_KILL takes: an explicit tuple of
    # device ids, or an int N = the LAST N devices of the engine's
    # mesh (the supervisor passes the mesh's device ids per-call, so
    # the loss is deterministic for a given mesh)
    lose: int | tuple = 1
    # WORKER_KILL with hard_kill=True calls os._exit(HARD_KILL_CODE)
    # instead of raising — the 2-subprocess harness's REAL process
    # death, which peers can only see through the heartbeat deadline
    # (lux_tpu/heartbeat.py)
    hard_kill: bool = False
    boundaries: int = dataclasses.field(default=0, init=False)
    fired: list = dataclasses.field(default_factory=list, init=False)
    # newest checkpoint generation the CKPT_* actions corrupt; bound
    # by the resilience supervisor (bind_checkpoint)
    ckpt_path: str | None = dataclasses.field(default=None, init=False)

    @classmethod
    def seeded(cls, seed: int, n: int = 16, p_crash: float = 0.25,
               p_delay: float = 0.0, p_nan: float = 0.0,
               delay_s: float = 0.0, nan_count: int = 1) -> "FaultPlan":
        """Derive a schedule over the first ``n`` boundaries from a
        seed — same seed, same faults, every run."""
        rng = np.random.default_rng(seed)
        schedule = {}
        for i in range(n):
            r = float(rng.random())
            if r < p_crash:
                schedule[i] = CRASH
            elif r < p_crash + p_delay:
                schedule[i] = DELAY
            elif r < p_crash + p_delay + p_nan:
                schedule[i] = NAN
        return cls(schedule=schedule, delay_s=delay_s,
                   nan_count=nan_count)

    def bind_checkpoint(self, path: str) -> None:
        """Point the CKPT_* actions at a run's checkpoint file (the
        resilience supervisor calls this with its checkpoint path)."""
        self.ckpt_path = path

    def _lost_ids(self, device_ids) -> tuple:
        """The device ids a DEVICE_LOSS/WORKER_KILL takes, resolved
        against the caller's mesh device ids (``lose`` int = the last
        N of them; explicit tuples pass through)."""
        if isinstance(self.lose, (tuple, list)):
            return tuple(int(d) for d in self.lose)
        ids = tuple(int(d) for d in (device_ids or ()))
        n = max(0, int(self.lose))
        # max(0, ...): lose >= the whole mesh takes EVERY device (a
        # negative slice start would wrap and under-report the loss)
        return ids[max(0, len(ids) - n):] if n and ids else ()

    def fire(self, state, int_value: int | None = None,
             device_ids=None):
        import os

        i = self.boundaries
        self.boundaries += 1
        action = self.schedule.get(i)
        if action is None:
            return None
        self.fired.append((i, action))
        if action == CRASH:
            raise InjectedWorkerCrash(
                f"injected worker crash at segment boundary {i}")
        if action == DELAY:
            time.sleep(self.delay_s)
            return None
        if action == NAN:
            return corrupt_state(
                state, self.nan_count,
                int_value if int_value is not None else self.int_value)
        if action == DEVICE_LOSS:
            lost = self._lost_ids(device_ids)
            raise InjectedDeviceLoss(
                f"injected device loss at segment boundary {i}: "
                f"devices {list(lost)} unavailable", lost)
        if action == WORKER_KILL:
            lost = self._lost_ids(device_ids)
            if self.hard_kill:
                # a REAL death: no exception, no cleanup, no goodbye —
                # exactly what a preempted/killed worker looks like to
                # its peers (heartbeat deadline, lux_tpu/heartbeat.py)
                os._exit(HARD_KILL_CODE)
            raise InjectedWorkerKill(
                f"injected worker death at segment boundary {i}: "
                f"coordination service heartbeat to the worker "
                f"holding devices {list(lost)} timed out", lost)
        if action in (CKPT_BITFLIP, CKPT_TRUNCATE):
            # the torn-write scenario: the on-disk newest generation
            # is damaged AND the worker dies — the retry's resume must
            # detect the corruption (CRC) and fall back one generation
            if self.ckpt_path and os.path.exists(self.ckpt_path):
                if action == CKPT_BITFLIP:
                    bitflip_checkpoint(self.ckpt_path)
                else:
                    truncate_checkpoint(self.ckpt_path)
            raise InjectedWorkerCrash(
                f"injected worker crash after {action} at segment "
                f"boundary {i}")
        raise ValueError(f"unknown fault action {action!r}")


@dataclasses.dataclass
class ReplicaKillPlan:
    """Replica-scoped kill schedule for the serving fleet
    (lux_tpu/fleet.py, round 18): ``schedule`` maps a replica NAME to
    the replica's segment-boundary index at which it dies.  The
    fleet's per-replica boundary hook calls ``fire(name)`` at every
    segment boundary of every runner the replica owns (one shared
    counter per replica, all query kinds), so the kill lands
    MID-DRAIN with queries resident in the runner's columns — exactly
    the in-flight state the failover path must re-dispatch.

    ``action`` is WORKER_KILL (default: InjectedWorkerKill, or with
    ``hard_kill=True`` a REAL ``os._exit(HARD_KILL_CODE)`` for
    subprocess replica workers — the genuine death only the replica
    board's beat staleness can detect) or DEVICE_LOSS
    (InjectedDeviceLoss).  A fired entry never re-fires (the
    boundary counter advances past it), so a drained fleet always
    terminates; ``fired`` records what happened, for assertions.

    Round 24 adds the self-healing drill actions: FLEET_CRASH (the
    whole fleet dies at the named replica's boundary — the typed
    InjectedFleetCrash propagates out of FleetServer.run, or
    ``hard_kill`` really exits; recovery is FleetServer.recover over
    the journals) and REPLICA_FLAP, the ONE re-firing action: the
    named replica dies at EVERY boundary from the scheduled index on
    (capped by ``flap_count`` firings, None = unbounded), so each
    resurrection dies again until the fleet's flap detection trips
    the typed quarantine — which stops the replica's boundaries and
    therefore terminates the plan.  Arm every schedule via
    ``FleetServer.routing_target`` per the round-22 rule (routing is
    a positive-feedback loop; a fixed replica index is a coin
    flip)."""

    schedule: dict
    action: str = WORKER_KILL
    hard_kill: bool = False
    # REPLICA_FLAP only: stop re-firing after this many kills (None =
    # keep killing until quarantine stops the boundaries)
    flap_count: int | None = None
    boundaries: dict = dataclasses.field(default_factory=dict,
                                         init=False)
    fired: list = dataclasses.field(default_factory=list, init=False)

    def __post_init__(self):
        # validate at CONSTRUCTION: a typo'd action discovered at
        # the scheduled boundary would crash the run mid-measurement
        # instead of failing the plan before anything was spent
        if self.action not in (WORKER_KILL, DEVICE_LOSS, FLEET_CRASH,
                               REPLICA_FLAP):
            raise ValueError(
                f"ReplicaKillPlan action must be WORKER_KILL, "
                f"DEVICE_LOSS, FLEET_CRASH, or REPLICA_FLAP, got "
                f"{self.action!r}")

    def fire(self, replica: str) -> None:
        import os

        i = int(self.boundaries.get(replica, 0))
        self.boundaries[replica] = i + 1
        due = self.schedule.get(replica)
        if due is None:
            return
        if self.action == REPLICA_FLAP:
            # the one re-firing action: every boundary AT/PAST the
            # scheduled index kills again, so a resurrected replica
            # dies at its first post-canary boundary — exactly the
            # flapping pattern quarantine detection exists for
            if i < int(due):
                return
            shots = sum(1 for r, _, _ in self.fired if r == replica)
            if self.flap_count is not None and shots >= self.flap_count:
                return
            self.fired.append((replica, i, self.action))
            raise InjectedWorkerKill(
                f"injected replica flap on serving replica "
                f"{replica!r} at its boundary {i} (death "
                f"{shots + 1}): coordination service heartbeat to "
                f"the replica timed out", ())
        if i != int(due):
            return
        self.fired.append((replica, i, self.action))
        if self.action == FLEET_CRASH:
            if self.hard_kill:
                # the REAL whole-fleet death: coordinator exits with
                # every replica's state — only the fsync'd journals
                # survive
                os._exit(HARD_KILL_CODE)
            raise InjectedFleetCrash(
                f"injected fleet crash at replica {replica!r} "
                f"boundary {i}: coordinator and all replicas died — "
                f"recover from the admission journal + mutation WAL",
                replica)
        if self.action == DEVICE_LOSS:
            raise InjectedDeviceLoss(
                f"injected device loss on serving replica "
                f"{replica!r} at its boundary {i}: devices "
                f"unavailable", ())
        if self.hard_kill:
            # a REAL death, mid-drain: no exception, no cleanup —
            # the parent fleet can only see it through the replica
            # board's beat going stale (lux_tpu/heartbeat.py)
            os._exit(HARD_KILL_CODE)
        raise InjectedWorkerKill(
            f"injected worker death on serving replica {replica!r} "
            f"at its boundary {i}: coordination service heartbeat "
            f"to the replica timed out", ())


@dataclasses.dataclass
class MutationFaultPlan:
    """Mutation-scoped fault schedule for the live-graph subsystem
    (lux_tpu/livegraph.py, round 20).  Two independent deterministic
    counters:

    - ``schedule`` maps a MUTATION-append index to MUT_CRASH (crash
      before the WAL record lands — the mutation must be absent from
      any replay) or WAL_TORN (a torn mid-append write: only a prefix
      of the record's bytes reach disk, then the crash — replay must
      detect the broken CRC chain, truncate the tail, and recover the
      exact pre-append state).
    - ``compact_schedule`` maps a COMPACTION index to COMPACT_CRASH
      (crash after the WAL COMPACT_START marker but before the atomic
      generation swap — recovery must come up on the SURVIVING
      generation, base + published delta, with the half-built
      generation discarded).
    - round 21: ``schedule`` also accepts the op-asserting crash legs
      MUT_DELETE/MUT_REWEIGHT (MUT_CRASH semantics, but the firing
      mutation's op must match — a typed ValueError otherwise), and
      ``reseed_schedule`` maps a RE-SEED index to RESEED_CRASH (crash
      between the affected-cone computation and the re-converge:
      recovery must come up with the anti-monotone ops still pending
      and admission still capped).

    Like FaultPlan, fired entries never re-fire (the counters advance
    past them), so recovery always terminates; ``fired`` records what
    happened, for assertions."""

    schedule: dict = dataclasses.field(default_factory=dict)
    compact_schedule: dict = dataclasses.field(default_factory=dict)
    reseed_schedule: dict = dataclasses.field(default_factory=dict)
    mutations: int = dataclasses.field(default=0, init=False)
    compactions: int = dataclasses.field(default=0, init=False)
    reseeds: int = dataclasses.field(default=0, init=False)
    fired: list = dataclasses.field(default_factory=list, init=False)

    # the op each op-asserting crash action demands of the firing
    # mutation (MUT_CRASH/WAL_TORN stay op-agnostic)
    _OP_BY_ACTION = {MUT_DELETE: "delete", MUT_REWEIGHT: "reweight"}

    def __post_init__(self):
        for i, a in self.schedule.items():
            if a not in (MUT_CRASH, WAL_TORN, MUT_DELETE,
                         MUT_REWEIGHT):
                raise ValueError(
                    f"MutationFaultPlan schedule[{i}] must be "
                    f"MUT_CRASH, WAL_TORN, MUT_DELETE, or "
                    f"MUT_REWEIGHT, got {a!r}")
        for i, a in self.compact_schedule.items():
            if a != COMPACT_CRASH:
                raise ValueError(
                    f"MutationFaultPlan compact_schedule[{i}] must "
                    f"be COMPACT_CRASH, got {a!r}")
        for i, a in self.reseed_schedule.items():
            if a != RESEED_CRASH:
                raise ValueError(
                    f"MutationFaultPlan reseed_schedule[{i}] must "
                    f"be RESEED_CRASH, got {a!r}")

    def fire_append(self, wal, record: bytes,
                    op: str = "append") -> None:
        """Called by LiveGraph._publish BEFORE the record is written.
        MUT_CRASH raises with nothing on disk; WAL_TORN writes a
        strict prefix of ``record`` (the torn write) and then raises;
        MUT_DELETE/MUT_REWEIGHT assert ``op`` matches, then crash
        like MUT_CRASH.  ``wal`` may be None (un-logged LiveGraph):
        the crash still fires, there is just nothing to tear."""
        i = self.mutations
        self.mutations += 1
        action = self.schedule.get(i)
        if action is None:
            return
        want = self._OP_BY_ACTION.get(action)
        if want is not None and op != want:
            raise ValueError(
                f"MutationFaultPlan schedule[{i}] = {action} expects "
                f"a {want!r} mutation at index {i}, but a {op!r} "
                f"fired — the drill's mutation stream is not the one "
                f"the plan was written against")
        self.fired.append((i, action))
        if action == WAL_TORN and wal is not None:
            wal.write_torn(record)
        raise InjectedWorkerCrash(
            f"injected {action} at mutation {i} (op={op}): worker "
            f"died "
            f"{'mid-append (torn WAL write)' if action == WAL_TORN else 'before the WAL record landed'}")

    def fire_compact(self) -> None:
        """Called by LiveGraph.compact between the COMPACT_START WAL
        marker and the atomic generation swap."""
        i = self.compactions
        self.compactions += 1
        if self.compact_schedule.get(i) != COMPACT_CRASH:
            return
        self.fired.append((i, COMPACT_CRASH))
        raise InjectedWorkerCrash(
            f"injected compact_crash at compaction {i}: worker died "
            f"after COMPACT_START, before the generation swap")

    def fire_reseed(self) -> None:
        """Called by LiveGraph._revalidate_anti between the
        affected-cone computation and the re-converge."""
        i = self.reseeds
        self.reseeds += 1
        if self.reseed_schedule.get(i) != RESEED_CRASH:
            return
        self.fired.append((i, RESEED_CRASH))
        raise InjectedWorkerCrash(
            f"injected reseed_crash at re-seed {i}: worker died "
            f"after the affected-cone computation, before the "
            f"re-converge")


def nan_corrupt(state, count: int = 1):
    """Host copy of ``state`` with NaN poked into the first ``count``
    cells of its first floating leaf (what a corrupted segment output
    looks like to debug.check_finite)."""
    import jax

    leaves, treedef = jax.tree.flatten(state)
    out, done = [], False
    for leaf in leaves:
        arr = np.array(leaf)              # host copy, always writable
        if (not done and arr.size
                and np.issubdtype(arr.dtype, np.floating)):
            arr.reshape(-1)[:count] = np.nan
            done = True
        out.append(arr)
    if not done:
        raise ValueError(
            "no floating leaf to NaN-corrupt (integer-labeled "
            "programs: use int_corrupt / corrupt_state with the "
            "program's identity sentinel)")
    return jax.tree.unflatten(treedef, out)


def int_corrupt(state, count: int = 1, value: int | None = None):
    """Host copy of ``state`` with ``value`` poked into the first
    ``count`` cells of its first INTEGER (non-bool) leaf — the
    one-sentinel convention's corruption for integer-labeled programs
    (sssp hop counts, components ids): poke the program's
    identity/sentinel, i.e. a lost update, never out-of-band garbage
    a max-program would propagate."""
    import jax

    if value is None:
        raise ValueError(
            "int_corrupt needs the program's identity/sentinel value "
            "(e.g. sssp.HOP_INF, components' -1)")
    leaves, treedef = jax.tree.flatten(state)
    out, done = [], False
    for leaf in leaves:
        arr = np.array(leaf)
        if (not done and arr.size
                and np.issubdtype(arr.dtype, np.integer)):
            arr.reshape(-1)[:count] = arr.dtype.type(value)
            done = True
        out.append(arr)
    if not done:
        raise ValueError("no integer leaf to corrupt")
    return jax.tree.unflatten(treedef, out)


def corrupt_state(state, count: int = 1, int_value: int | None = None):
    """Type-appropriate state corruption: NaN into the first float
    leaf when one exists, else the sentinel ``int_value`` into the
    first integer leaf — what makes every app corruption-testable
    under a seeded ``p_nan`` plan (the old float-only nan_corrupt
    crashed the harness on sssp/components)."""
    import jax

    if any(np.issubdtype(np.asarray(x).dtype, np.floating)
           for x in jax.tree.leaves(state)):
        return nan_corrupt(state, count)
    return int_corrupt(state, count, int_value)


# -- checkpoint-file injectors (exercise checkpoint.py's CRC +
#    generation-fallback path deterministically) -----------------------

def bitflip_checkpoint(path: str, leaf: int = 0, bit: int = 0) -> None:
    """Flip one bit in ``leaf``'s payload INSIDE the npz container,
    rewriting the zip so its own member CRC stays consistent — the
    torn-but-well-formed corruption only checkpoint.py's per-leaf
    CRC32 can catch (a raw on-disk flip would already fail the zip
    layer).  The flipped bit is in the last payload byte, safely past
    the .npy header."""
    import io
    import zipfile

    name = f"leaf_{leaf}.npy"
    with zipfile.ZipFile(path, "r") as z:
        items = [(zi.filename, z.read(zi.filename))
                 for zi in z.infolist()]
    out = io.BytesIO()
    with zipfile.ZipFile(out, "w", zipfile.ZIP_STORED) as z:
        for fname, data in items:
            if fname == name:
                data = bytearray(data)
                data[-1] ^= (1 << (bit & 7))
                data = bytes(data)
            z.writestr(fname, data)
    with open(path, "wb") as f:
        f.write(out.getvalue())


def truncate_checkpoint(path: str, keep: float = 0.5) -> None:
    """Truncate the file to ``keep`` of its size — the torn-write /
    partial-download corruption (an unreadable container, caught by
    checkpoint.load's CorruptCheckpointError wrapping)."""
    import os

    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(1, int(size * keep)))


def tear_wal(path: str, keep_bytes: int = 7) -> None:
    """Append ``keep_bytes`` of a partial garbage record to a
    mutation log at rest — what a power loss mid-append leaves on
    disk (the torn tail scripts/fsck_lux.py and MutationLog.replay
    must diagnose via the CRC chain, never replay).  A mid-append
    tear is by definition a STRICT record prefix, so keep_bytes is
    clamped below the record size — a full-record-sized garbage
    tail would read as a complete record with a bad CRC, which
    MutationLog.scan rightly classifies as hard crc_chain
    corruption of a possibly-acknowledged mutation, not the
    recoverable torn tail this helper promises."""
    from lux_tpu import format as luxfmt

    with open(path, "ab") as f:
        f.write(b"\x7f" * min(max(1, int(keep_bytes)),
                              luxfmt.WAL_RECORD_SIZE - 1))
