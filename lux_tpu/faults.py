"""Deterministic fault injection for the resilience layer.

The tunnel's real failure modes — transient TPU worker death (a
pagerank-mp sample collapsed 10x in BENCH_r05 and one whole config
crashed during round 5), slow segments, and state corruption — do not
reproduce on demand, so the recovery paths that handle them would
otherwise ship untested.  This module injects synthetic versions of
those failures at SEGMENT BOUNDARIES on a deterministic schedule
(explicit, or derived from a seed), so the whole
classify/retry/resume path (lux_tpu/resilience.py) is exercised by
the CPU test suite.

Faults key on a global boundary COUNTER, not on iteration numbers:
after a crash-and-resume the counter has advanced past the fired
fault, so a schedule never re-fires and every supervised run
terminates.  The counter also persists across the supervisor's
retries, which is what makes a seeded schedule reproducible
end-to-end.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

CRASH = "crash"     # raise InjectedWorkerCrash (retryable)
DELAY = "delay"     # sleep delay_s (exercises slow-segment paths)
NAN = "nan"         # NaN-corrupt the first floating state leaf


class InjectedWorkerCrash(RuntimeError):
    """Synthetic analogue of the tunnel's transient worker death;
    resilience.classify treats it as retryable."""


@dataclasses.dataclass
class FaultPlan:
    """A deterministic boundary-counter -> action schedule.

    ``fire(state)`` is called by the supervisor at every segment
    boundary.  It returns None (no state change), or a HOST-side
    corrupted copy of the state pytree (the caller re-places it on
    device); a scheduled CRASH raises InjectedWorkerCrash before the
    segment's checkpoint save; a scheduled DELAY sleeps.  ``fired``
    records what actually happened, for assertions.
    """

    schedule: dict
    delay_s: float = 0.0
    nan_count: int = 1
    boundaries: int = dataclasses.field(default=0, init=False)
    fired: list = dataclasses.field(default_factory=list, init=False)

    @classmethod
    def seeded(cls, seed: int, n: int = 16, p_crash: float = 0.25,
               p_delay: float = 0.0, p_nan: float = 0.0,
               delay_s: float = 0.0, nan_count: int = 1) -> "FaultPlan":
        """Derive a schedule over the first ``n`` boundaries from a
        seed — same seed, same faults, every run."""
        rng = np.random.default_rng(seed)
        schedule = {}
        for i in range(n):
            r = float(rng.random())
            if r < p_crash:
                schedule[i] = CRASH
            elif r < p_crash + p_delay:
                schedule[i] = DELAY
            elif r < p_crash + p_delay + p_nan:
                schedule[i] = NAN
        return cls(schedule=schedule, delay_s=delay_s,
                   nan_count=nan_count)

    def fire(self, state):
        i = self.boundaries
        self.boundaries += 1
        action = self.schedule.get(i)
        if action is None:
            return None
        self.fired.append((i, action))
        if action == CRASH:
            raise InjectedWorkerCrash(
                f"injected worker crash at segment boundary {i}")
        if action == DELAY:
            time.sleep(self.delay_s)
            return None
        if action == NAN:
            return nan_corrupt(state, self.nan_count)
        raise ValueError(f"unknown fault action {action!r}")


def nan_corrupt(state, count: int = 1):
    """Host copy of ``state`` with NaN poked into the first ``count``
    cells of its first floating leaf (what a corrupted segment output
    looks like to debug.check_finite)."""
    import jax

    leaves, treedef = jax.tree.flatten(state)
    out, done = [], False
    for leaf in leaves:
        arr = np.array(leaf)              # host copy, always writable
        if (not done and arr.size
                and np.issubdtype(arr.dtype, np.floating)):
            arr.reshape(-1)[:count] = np.nan
            done = True
        out.append(arr)
    if not done:
        raise ValueError(
            "no floating leaf to NaN-corrupt (integer-labeled "
            "programs need a CRASH/DELAY fault instead)")
    return jax.tree.unflatten(treedef, out)
