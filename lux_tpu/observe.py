"""Performance observatory: calibrated measurement as a subsystem.

The repo's numbers have been produced by ~20 one-off
``scripts/profile_*.py`` runs and hand-assembled bench artifacts,
while PERF_NOTES documents three standing measurement traps — 10x
tunnel-session variance, XLA loop-invariant hoisting, early
``block_until_ready`` returns — that have each burned a round.  This
module makes trustworthy measurement a first-class capability with
three pillars (the microbenchmark-driven methodology of the IPU
dissection paper, PAPERS.md, is the exemplar):

1. **Session calibration** (``calibrate``): a fixed-cost reference
   probe — the canonical small-table gather and a pair-dot MXU
   microkernel at PINNED shapes, measured with the trusted recipe
   (loop-dependent inputs, scalar outputs, one jit, host-fetch fence;
   ``timing.loop_bench``) — runs once per process and yields a
   ``Fingerprint``: measured ns/elem vs the canonical PERF_NOTES
   figures, platform/backend, device count, session id and a static
   audit of the probe programs.  Every bench metric line and ledger
   record carries its digest, so a 10x-slow tunnel session is
   DETECTED AND LABELED ("degraded") instead of silently polluting
   the trajectory; ``scripts/check_bench.py`` rejects metric lines
   from non-"canonical" sessions.

2. **Phase-cost attribution** (``decompose``): the
   profile_cliff/profile_true/profile_owner methodology as a library
   API — one engine iteration split into its ``timed_phases`` phases
   (exchange / gather / reduce / apply, owner ``gen_exchange``, push
   relax/update, dot_reduce), each phase measured median-of-k with a
   MAD noise estimate and compared against ``scalemodel.phase_model``
   predictions RESCALED to this session's measured primitive rate
   (``session_scale``).  Divergence beyond the variance-aware bound
   becomes a typed drift verdict (``drift_slow``/``drift_fast``) and
   a ``drift`` telemetry event; phases without a measured constant
   are honestly ``unmodeled``.

3. **Persistent perf ledger** (``PerfLedger``): an append-only JSONL
   (default ``PERFLEDGER.jsonl``) of calibrated samples — probe
   figures, phase decompositions, bench metric lines, collected
   debts — each stamped with the session fingerprint, plus a
   carried-debt registry (``DEBTS``) encoding the ROADMAP's owed
   on-device measurements so any live-tunnel session can
   ``collect_debts`` for whichever match its topology.

Round 19 grows the COMM side of each pillar (lux_tpu/comms.py): a
measured link calibration (``calibrate_links`` — ppermute/all_to_all
payload sweeps on the same loop_bench recipe, feeding
``scalemodel.set_measured_link`` on canonical platforms only), a
per-app comm-attribution verdict inside ``decompose`` (the engine's
oracle-checked byte ledger vs the measured exchange phases — the
wire time is a LOWER bound, so a phase beating its own bytes is the
contradiction), and the ici/dcn bandwidth debts.

CLI: ``python -m lux_tpu.observe`` emits a calibrated
phase-decomposition report for all four apps with drift verdicts
(CPU-runnable; tier-1 smoke in tests/test_observe.py).

Reference anchor: the reference's only measurement is -verbose wall
clocks (reference sssp_gpu.cu:513-518); this subsystem is what a
claims-bearing TPU port needs instead.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from statistics import median

import numpy as np

from lux_tpu import scalemodel, telemetry
from lux_tpu.timing import loop_bench

SCHEMA = 1
LEDGER_DEFAULT = "PERFLEDGER.jsonl"
LEDGER_KINDS = ("probe", "phase", "bench", "debt")

# Platforms the canonical figures were measured on (the axon tunnel
# presents the chip as either name depending on the jax version).
CANONICAL_PLATFORMS = frozenset({"tpu", "axon"})

# Probe shapes are PINNED: a calibration figure is only comparable
# across sessions if every session measures the identical program.
PROBE_GATHER_LOGV = 18        # 1 MB f32 table — small-table regime
PROBE_GATHER_N = 1 << 20      # 1M indices per step
PROBE_DOT_ROWS = 256          # pair-dot rows per step
PROBE_DOT_K = 20              # colfilter's K (the modeled 5.5 ns/K)
PROBE_PAGE_ROWS = 2048        # paged-gather delivery rows per step
PROBE_PAGE_TABLE = 256        # pages in the probe's page buffer
PROBE_LOOP_K = 8              # steps inside the one jitted loop
DEVIATION_BOUND = 3.0         # outside [1/3, 3]x of canon = degraded

# Canonical figures (ns per unit) for the probe kernels.  The gather
# figure is MEASURED (PERF_NOTES round 2, 8.96 ns/elem v5e small
# table) and is the figure that grades a session; the pair-dot figure
# is the round-8 MODEL (5.5 ns/K per row), carried as a debt below
# until the on-device sweep pins it — it is recorded for trajectory
# but never gates.
CANONICAL = {
    "gather_small_ns": scalemodel.GATHER_SMALL_NS,
    "pair_dot_row_ns": scalemodel.PAIR_DOT_ROW_K_NS * PROBE_DOT_K,
    # paged-gather delivery row (ops/pagegather.py): row fetch + the
    # 128-lane shuffle + the compare-reduce, composed from MEASURED
    # primitive figures (PERF_NOTES round 2: 24 ns/row static fetch,
    # 0.38 ns/elem shuffle, the 150 ns pair-row machinery the paged
    # row shares) — scalemodel.PAGED_ROW_NS.  A model until the
    # on-device A/B lands (DEBTS "paged-gather-ab"); recorded for
    # trajectory and the paged phase pricing, never grading.
    "page_gather_row_ns": scalemodel.PAGED_ROW_NS,
}


# ---------------------------------------------------------------------
# robust statistics

def median_mad(xs):
    """(median, median-absolute-deviation) — the variance-aware pair
    every observatory comparison uses instead of mean/stdev (tunnel
    collapses are heavy-tailed; one 10x sample must not drag the
    estimate, PERF_NOTES round 5)."""
    xs = list(xs)
    if not xs:
        raise ValueError("median_mad of an empty sample set")
    m = median(xs)
    return m, median(abs(x - m) for x in xs)


def drift_verdict(samples, predicted_s, bound: float = DEVIATION_BOUND):
    """Compare measured seconds against a model prediction with a
    variance-aware bound: the base ``bound`` ratio widens by the
    samples' relative MAD (a noisy phase must diverge FURTHER before
    it is called drift — 1.4826*MAD estimates sigma for normal noise).
    Returns "ok" | "drift_slow" | "drift_fast" | "unmodeled"."""
    if predicted_s is None or predicted_s <= 0:
        return "unmodeled"
    m, mad = median_mad(samples)
    if m <= 0:
        return "unmodeled"
    eff = bound * (1.0 + 3.0 * 1.4826 * mad / m)
    ratio = m / predicted_s
    if ratio > eff:
        return "drift_slow"
    if ratio < 1.0 / eff:
        return "drift_fast"
    return "ok"


# ---------------------------------------------------------------------
# pillar 1: session calibration

@dataclasses.dataclass(frozen=True)
class Fingerprint:
    """One process's calibration: measured probe rates vs canon.

    ``grade``: "canonical" (canonical platform, gather probe within
    ``DEVIATION_BOUND`` of the PERF_NOTES figure), "degraded"
    (canonical platform, outside the bound — the 10x tunnel session,
    detected), "uncalibrated" (a platform with no canonical figures,
    e.g. the CPU test mesh — measured rates recorded, never compared
    into the trajectory)."""

    schema: int
    session: str              # telemetry.session_id()
    pid: int
    backend: str              # jax.default_backend()
    platform: str             # jax.devices()[0].platform
    ndev: int
    probe: dict               # measured {name_ns, name_mad_ns}
    canonical: dict           # the figures of record (CANONICAL)
    deviation: float          # gather probe / canonical gather
    grade: str
    audit: dict               # static audit digest of the probe jaxprs

    def digest(self) -> dict:
        """The compact JSON field metric lines and ledger records
        carry (scripts/check_bench.py validates it)."""
        return {
            "schema": self.schema, "session": self.session,
            "platform": self.platform, "backend": self.backend,
            "ndev": self.ndev, "grade": self.grade,
            "deviation": round(self.deviation, 4),
            "probe": {k: round(v, 3) for k, v in self.probe.items()},
            "audit": {"errors": self.audit.get("errors", 0),
                      "warnings": self.audit.get("warnings", 0)},
        }


def _gather_probe_carry():
    import jax.numpy as jnp
    rng = np.random.default_rng(0)            # pinned seed: one program
    v = 1 << PROBE_GATHER_LOGV
    table = jnp.asarray(rng.random(v, np.float32))
    idx = jnp.asarray(
        rng.integers(0, v, PROBE_GATHER_N).astype(np.int32))
    return table, idx


def _gather_probe_step(carry):
    import jax.numpy as jnp
    table, idx = carry
    sv = jnp.sum(jnp.take(table, idx, axis=0))
    return sv, (table + sv * 1e-30, idx)


def _page_resolve_method() -> str:
    """The paged resolution formulation this platform runs: the
    Pallas lane-shuffle kernel on real TPUs, the plain XLA
    take_along_axis everywhere else (matching the engines'
    resolve_reduce_method split, engine/pull.py)."""
    import jax
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def _page_probe_carry():
    import jax.numpy as jnp
    rng = np.random.default_rng(2)           # pinned seed: one program
    table = jnp.asarray(
        rng.random((PROBE_PAGE_TABLE, 128), np.float32))
    slot = rng.integers(0, PROBE_PAGE_TABLE, PROBE_PAGE_ROWS)
    lane = rng.integers(0, 128, (PROBE_PAGE_ROWS, 128))
    sl = (slot[:, None].astype(np.uint32) << np.uint32(7)) \
        | lane.astype(np.uint32)
    rel = rng.integers(0, 128, (PROBE_PAGE_ROWS, 128)).astype(np.int8)
    return table, jnp.asarray(sl), jnp.asarray(rel)


def _page_probe_step(carry):
    """One paged DELIVERY row pipeline per row: page-row fetch, lane
    shuffle, compare-reduce — the full composed primitive the engines
    run per row (ops/pagegather.paged_partial), so the session scale
    this probe yields prices paged phases in THIS session's ns."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.ops.pagegather import lane_resolve
    from lux_tpu.ops.tiled import chunk_partials
    table, sl, rel = carry
    row_slot = jax.lax.shift_right_logical(
        sl[:, 0], jnp.uint32(7)).astype(jnp.int32)
    rows = jnp.take(table, row_slot, axis=0)
    vals = lane_resolve(rows, sl, _page_resolve_method())
    vals = jax.lax.optimization_barrier(vals)
    partials = chunk_partials(vals, rel, 128, "sum")
    sv = jnp.sum(partials)
    return sv, (table + sv * 1e-30, sl, rel)


def _dot_probe_carry(kdim: int = PROBE_DOT_K):
    import jax.numpy as jnp
    rng = np.random.default_rng(1)
    shape = (PROBE_DOT_ROWS, 128, kdim)
    s = jnp.asarray(rng.random(shape, np.float32))
    t = jnp.asarray(rng.random(shape, np.float32))
    return s, t


def _dot_probe_step(carry):
    import jax.numpy as jnp
    s, t = carry
    # the pair-dot delivery's MXU core: D = S @ T^T per row
    d = jnp.einsum("rik,rjk->rij", s, t)
    sv = jnp.sum(d)
    return sv, (s + sv * 1e-30, t)


def _audit_probe_programs():
    """Static audit of the probe jaxprs (lux_tpu/audit.py): the
    calibration subsystem must satisfy the same structural invariants
    it exists to referee — a probe with a hoistable loop body or a
    baked-in multi-MB constant would measure nothing."""
    import jax
    import jax.numpy as jnp

    from lux_tpu import audit

    findings = []
    for name, step, carry in (
            ("gather", _gather_probe_step, _gather_probe_carry()),
            ("pair_dot", _dot_probe_step, _dot_probe_carry()),
            ("page_gather", _page_probe_step, _page_probe_carry())):
        def run(c0, _step=step):
            def body(_, c):
                acc, cur = c
                sv, cur = _step(cur)
                return acc + sv, cur
            return jax.lax.fori_loop(0, PROBE_LOOP_K, body,
                                     (jnp.float32(0), c0))[0]
        closed = jax.make_jaxpr(run)(carry)
        findings += audit.audit_jaxpr(closed,
                                      where=f"observe.probe_{name}")
    return audit.digest(findings, mode="error"), findings


def _grade(platform: str, deviation: float,
           bound: float = DEVIATION_BOUND) -> str:
    if platform not in CANONICAL_PLATFORMS:
        return "uncalibrated"
    if deviation > bound or deviation < 1.0 / bound:
        return "degraded"
    return "canonical"


_FP: Fingerprint | None = None


def calibrate(force: bool = False, clock=time.perf_counter,
              repeats: int = 3) -> Fingerprint:
    """Run the reference probe ONCE per process (cached; ``force``
    re-runs, e.g. after a suspected tunnel degradation mid-session)
    and return the session Fingerprint.  Cost: two tiny jits + a few
    warm re-executions — O(100 ms) on-chip, a couple of seconds on
    the CPU test mesh.  ``clock`` is injectable for deterministic
    tests."""
    global _FP
    if _FP is not None and not force:
        return _FP
    import jax

    gather_s, _ = loop_bench(_gather_probe_step, _gather_probe_carry(),
                             PROBE_LOOP_K, repeats=repeats, clock=clock)
    dot_s, _ = loop_bench(_dot_probe_step, _dot_probe_carry(),
                          PROBE_LOOP_K, repeats=repeats, clock=clock)
    page_s, _ = loop_bench(_page_probe_step, _page_probe_carry(),
                           PROBE_LOOP_K, repeats=repeats, clock=clock)
    g_m, g_mad = median_mad(gather_s)
    d_m, d_mad = median_mad(dot_s)
    p_m, p_mad = median_mad(page_s)
    probe = {
        "gather_small_ns": g_m / PROBE_GATHER_N * 1e9,
        "gather_small_mad_ns": g_mad / PROBE_GATHER_N * 1e9,
        "pair_dot_row_ns": d_m / PROBE_DOT_ROWS * 1e9,
        "pair_dot_row_mad_ns": d_mad / PROBE_DOT_ROWS * 1e9,
        "page_gather_row_ns": p_m / PROBE_PAGE_ROWS * 1e9,
        "page_gather_row_mad_ns": p_mad / PROBE_PAGE_ROWS * 1e9,
    }
    deviation = probe["gather_small_ns"] / CANONICAL["gather_small_ns"]
    platform = jax.devices()[0].platform
    audit_digest, _findings = _audit_probe_programs()
    fp = Fingerprint(
        schema=SCHEMA, session=telemetry.session_id(), pid=os.getpid(),
        backend=jax.default_backend(), platform=platform,
        ndev=len(jax.devices()), probe=probe, canonical=dict(CANONICAL),
        deviation=deviation, grade=_grade(platform, deviation),
        audit=audit_digest)
    telemetry.current().emit("calibration", **fp.digest())
    _FP = fp
    return fp


def fingerprint_digest(fp: Fingerprint | None = None) -> dict:
    """The ``calibration`` field for a metric line: digest of ``fp``
    (or of this process's cached/fresh calibration)."""
    return (fp or calibrate()).digest()


def session_scale(fp: Fingerprint) -> float:
    """Factor rescaling the scalemodel's canonical-TPU constants into
    THIS session's nanoseconds: the measured gather probe over the
    canonical figure.  ~1.0 on a healthy tunnel; ~10 on a degraded
    one; whatever the host costs on the CPU mesh — which is exactly
    what lets a CPU phase decomposition carry meaningful verdicts."""
    return fp.probe["gather_small_ns"] / fp.canonical["gather_small_ns"]


# ---------------------------------------------------------------------
# pillar 1b: measured link calibration (round 19, lux_tpu/comms.py)

# payload sizes (f32 elems PER DEVICE) for the link sweep: small
# enough that the CPU mesh finishes in ~a second, large enough that
# the top size amortizes launch overhead into a bandwidth figure
LINK_PAYLOAD_ELEMS = (1 << 12, 1 << 16, 1 << 20)

# tier -> measured record of THIS session ({"bytes_per_s", "prim",
# "payload_bytes", "sweep"}); None until calibrate_links ran
_LINKS: dict = {}


def _link_step(mesh, prim: str):
    """One collective launch per loop step, payload riding the carry
    (the loop_bench contract: loop-dependent, never hoistable).  The
    probe measures the wire, so the collective lives HERE rather than
    in ops/ — the scope lint is deliberately waived."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    nd = int(mesh.devices.size)
    axis = mesh.axis_names[0]
    perm = [(j, (j + 1) % nd) for j in range(nd)]

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=P(axis),
                       out_specs=P(axis))
    def hop(v):
        if prim == "ppermute":
            # audit: allow(collective-scope) — the link probe IS the
            # measurement; there is no engine program to ride
            return jax.lax.ppermute(v, axis, perm)
        blk = v.reshape(nd, -1)
        # audit: allow(collective-scope) — link probe (see above)
        return jax.lax.all_to_all(blk, axis, split_axis=0,
                                  concat_axis=0,
                                  tiled=True).reshape(v.shape)

    def step(carry):
        y = hop(carry)
        sv = jnp.sum(y.reshape(-1)[:8].astype(jnp.float32))
        return sv, y

    return step


def calibrate_links(payload_elems=LINK_PAYLOAD_ELEMS,
                    repeats: int = 3,
                    clock=time.perf_counter) -> dict:
    """Measure this session's link rate with ppermute-ring and
    all_to_all payload sweeps on the trusted ``timing.loop_bench``
    recipe (one jit, loop-dependent carry, scalar-fetch fence).
    Returns {tier: record} — empty when fewer than 2 devices are
    visible.  The headline ``bytes_per_s`` is the peak measured
    ppermute rate (per-device wire bytes over seconds/step).  On a
    CANONICAL platform the figure is fed into
    ``scalemodel.set_measured_link`` so the mesh projections price
    from the measurement (the round-19 replacement for the hardcoded
    ICI_BYTES_PER_S); elsewhere it is recorded and labeled, never fed
    — a CPU-mesh memcpy rate must not price a pod."""
    import jax

    if len(jax.devices()) < 2:
        return {}
    from lux_tpu import comms, scalemodel
    from lux_tpu.parallel.mesh import make_mesh

    nd = len(jax.devices())
    mesh = make_mesh(nd)
    tier = comms.mesh_tier(mesh)
    platform = jax.devices()[0].platform
    sweep = {}
    best = (0.0, None, 0)
    for prim in ("ppermute", "all_to_all"):
        step = _link_step(mesh, prim)
        for elems in payload_elems:
            rng = np.random.default_rng(11)
            carry = rng.random(nd * int(elems), np.float32)
            samples, _ = loop_bench(step, carry, PROBE_LOOP_K,
                                    repeats=repeats, clock=clock)
            m, mad = median_mad(samples)
            payload = int(elems) * 4       # per-device f32 bytes
            wire = comms.shipped_bytes(prim, payload, nd)
            rate = wire / m if m > 0 else 0.0
            sweep[f"{prim}@{payload}"] = {
                "s_per_step": round(m, 6),
                "mad_s": round(mad, 6),
                "bytes_per_s": round(rate, 1)}
            if prim == "ppermute" and rate > best[0]:
                best = (rate, prim, payload)
    rec = {"tier": tier, "bytes_per_s": best[0], "prim": best[1],
           "payload_bytes": best[2], "ndev": nd,
           "platform": platform, "sweep": sweep,
           "fed_scalemodel": platform in CANONICAL_PLATFORMS}
    _LINKS[tier] = rec
    if rec["fed_scalemodel"] and best[0] > 0:
        scalemodel.set_measured_link(tier, best[0])
    telemetry.current().emit(
        "link_calibration", tier=tier, ndev=nd, platform=platform,
        bytes_per_s=round(best[0], 1), prim=best[1],
        payload_bytes=best[2], fed_scalemodel=rec["fed_scalemodel"])
    return dict(_LINKS)


def link_rate(tier: str = "ici") -> float | None:
    """This session's measured link rate for ``tier`` (bytes/s), or
    None when calibrate_links never measured one."""
    rec = _LINKS.get(tier)
    return rec["bytes_per_s"] if rec else None


# ---------------------------------------------------------------------
# pillar 2: phase-cost attribution

# timed_phases report keys that are counters, not phase seconds
META_KEYS = ("frontier", "bucket", "advances")


@dataclasses.dataclass(frozen=True)
class PhaseCost:
    phase: str
    samples: tuple            # seconds, one per measured iteration
    median_s: float
    mad_s: float
    predicted_s: float | None  # session-scaled model; None = unmodeled
    ratio: float | None        # median / predicted
    verdict: str               # ok | drift_slow | drift_fast | unmodeled


@dataclasses.dataclass(frozen=True)
class AppDecomposition:
    app: str
    engine: str               # "pull" | "push"
    exchange: str
    ne: int
    nv: int
    iters: int
    session: str
    scale: float              # session_scale applied to the model
    phases: tuple             # PhaseCost, report order
    comm: dict | None = None  # round-19 comm attribution (ledger
    #                           bytes, measured exchange phase vs the
    #                           wire lower bound, verdict)

    def as_dict(self) -> dict:
        return {
            "app": self.app, "engine": self.engine,
            "exchange": self.exchange, "ne": self.ne, "nv": self.nv,
            "iters": self.iters, "session": self.session,
            "scale": round(self.scale, 4),
            "comm": self.comm,
            "phases": [{
                "phase": p.phase,
                "median_s": round(p.median_s, 6),
                "mad_s": round(p.mad_s, 6),
                "predicted_s": (None if p.predicted_s is None
                                else round(p.predicted_s, 6)),
                "ratio": (None if p.ratio is None
                          else round(p.ratio, 3)),
                "verdict": p.verdict,
            } for p in self.phases],
        }


def _engine_kind(eng) -> str:
    return "push" if hasattr(eng, "converge") else "pull"


def _engine_model(eng, scale: float,
                  page_scale: float | None = None) -> dict:
    """scalemodel.phase_model priced from the engine's OWN layout
    stats (pair coverage/inflation, owner chunk inflation, K-dim,
    the paged plan's page ratio/fill) — the same stats the engines
    already report."""
    cov, row_infl = 0.0, 1.0
    if eng.pairs is not None:
        cov = float(eng.pairs.stats["coverage"])
        row_infl = max(1.0, float(eng.pairs.stats["inflation"]))
    chunk_infl = 1.2
    owner = getattr(eng, "owner", None)
    if owner is not None and getattr(owner, "stats", None):
        chunk_infl = max(1.0, float(owner.stats["chunk_inflation"]))
    state_bytes = getattr(eng.program, "state_bytes", None) or 4
    kdim = max(1, int(state_bytes) // 4)
    dot = getattr(eng.program, "edge_value_from_dot", None) is not None
    pp = getattr(eng, "page_plan", None)
    paged = pp is not None
    # MXU reduce pricing (round 23): the engine's RESOLVED use_mxu
    # flag and its K x B payload width — with it the "reduce" phase
    # gets a modeled figure instead of None (unmodeled), so decompose
    # grades the contraction's drift like every other phase
    from lux_tpu.engine.pull import mxu_wide_of
    return scalemodel.phase_model(
        engine=_engine_kind(eng), exchange=eng.exchange,
        ne=int(eng.sg.ne), nv=int(eng.sg.nv), kdim=kdim,
        pair_coverage=cov, pair_row_inflation=row_infl,
        chunk_inflation=chunk_infl,
        state_bytes_per_vertex=int(state_bytes), dot=dot, scale=scale,
        use_mxu=bool(getattr(eng, "use_mxu", False)),
        mxu_wide=mxu_wide_of(eng.program),
        reduce_kind=getattr(eng.program, "reduce", "sum"),
        paged=paged,
        page_ratio=float(pp.stats["page_ratio"]) if paged else 0.0,
        page_fill=float(pp.stats.get("padded_fill",
                                     pp.stats["fill"]))
        if paged else 128.0,
        page_scale=page_scale,
        page_mode=pp.mode if paged else "paged",
        page_g_fill=float(pp.stats.get("padded_g_fill", 128.0))
        if paged else 128.0)


def decompose(eng, app: str, iters: int = 3,
              fingerprint: Fingerprint | None = None,
              bound: float = DEVIATION_BOUND) -> AppDecomposition:
    """Measure one engine's per-iteration phase split (median-of-
    ``iters`` + MAD per phase) and attribute each phase against the
    session-scaled scalemodel prediction.

    Instrumentation is a pure observer: phases run on their own state
    copies (``timed_phases``), the engine's compiled programs and
    graph arrays are untouched, and a run after ``decompose`` is
    bitwise identical to one without it (tests/test_observe.py, the
    audit no-op proof pattern).  Emits one ``phase_cost`` event per
    phase and a ``drift`` event per non-ok verdict."""
    fp = fingerprint or calibrate()
    scale = session_scale(fp)
    page_scale = None
    if "page_gather_row_ns" in fp.probe:
        page_scale = (fp.probe["page_gather_row_ns"]
                      / fp.canonical["page_gather_row_ns"])
    model = _engine_model(eng, scale, page_scale=page_scale)
    kind = _engine_kind(eng)
    tel = telemetry.current()

    def run_phases(n):
        if kind == "push":
            label, active = eng.init_state()
            _l, _a, rep = eng.timed_phases(label, active, n)
        else:
            _s, rep = eng.timed_phases(eng.init_state(), n)
        return rep

    # Warm with the SAME full iteration trajectory that will be
    # measured: push engines switch sparse->dense phase programs as
    # the frontier evolves, so a one-iteration warmup would leave
    # later phase programs to compile INSIDE the measured window
    # (both runs start from init_state, so the trajectories — and
    # therefore the compiled-program coverage — are identical).
    run_phases(iters)
    report = run_phases(iters)

    # the raw per-iteration report rides the event trail in the CLI's
    # ``phases`` shape (lux_tpu/cli.py), so tracing renders phase
    # spans — and, with the comm_ledger event below, subdivides the
    # exchange phases into per-collective spans — from a decompose
    # run's log exactly like from a CLI -phases run
    tel.emit("phases", app=app, iters=len(report),
             report=[{k: (v if k in META_KEYS else round(float(v), 6))
                      for k, v in entry.items()} for entry in report])

    by_phase: dict[str, list] = {}
    for entry in report:
        for k, v in entry.items():
            if k not in META_KEYS:
                by_phase.setdefault(k, []).append(float(v))

    phases = []
    for name, samples in by_phase.items():
        m, mad = median_mad(samples)
        pred_ns = model.get(name)
        pred = None if pred_ns is None else pred_ns * 1e-9
        verdict = drift_verdict(samples, pred, bound=bound)
        ratio = None if not pred else m / pred
        pc = PhaseCost(phase=name, samples=tuple(samples), median_s=m,
                       mad_s=mad, predicted_s=pred, ratio=ratio,
                       verdict=verdict)
        phases.append(pc)
        tel.emit("phase_cost", app=app, phase=name,
                 median_s=round(m, 6), mad_s=round(mad, 6),
                 predicted_s=None if pred is None else round(pred, 6),
                 verdict=verdict)
        if verdict.startswith("drift"):
            tel.emit("drift", app=app, phase=name, verdict=verdict,
                     measured_s=round(m, 6), predicted_s=round(pred, 6),
                     ratio=round(m / pred, 3), session=fp.session)
    comm = _comm_attribution(eng, app, phases, tel)
    return AppDecomposition(
        app=app, engine=kind, exchange=eng.exchange, ne=int(eng.sg.ne),
        nv=int(eng.sg.nv), iters=iters, session=fp.session,
        scale=scale, phases=tuple(phases), comm=comm)


def _comm_attribution(eng, app: str, phases, tel) -> dict:
    """Round-19 comm verdict: the engine's per-collective byte ledger
    (lux_tpu/comms.ledger_for — oracle- and audit-cross-checked, a
    broken build raises its typed CommLedgerError through here) vs
    the measured exchange-family phases.  The wire time
    (ledger bytes / this session's MEASURED link rate) is a LOWER
    bound on the exchange phase — generation/apply compute rides the
    same phase, so only a phase FASTER than its own bytes is a
    contradiction (``drift_fast``); with no measured link rate the
    verdict is honestly ``unmodeled``, and off-mesh it is
    ``no-comm``."""
    from lux_tpu import comms

    led = comms.ledger_for(eng)
    exch_names = getattr(eng, "COMM_PHASES",
                         ("exchange", "gen_exchange"))
    exch = [p for p in phases if p.phase in exch_names]
    measured = sum(p.median_s for p in exch) if exch else None
    rate = link_rate(led.tier) if led.tier != "local" else None
    pred = None
    if rate and led.bytes_per_iter:
        pred = led.bytes_per_iter / rate
    if led.bytes_per_iter == 0:
        verdict = "no-comm"
    elif pred is None or measured is None:
        verdict = "unmodeled"
    elif measured < pred / DEVIATION_BOUND:
        verdict = "drift_fast"
    else:
        verdict = "ok"
    comm = {
        "bytes_per_iter": led.bytes_per_iter,
        "bytes_per_edge": round(led.bytes_per_edge, 6),
        "messages": led.messages, "tier": led.tier,
        "per_collective": led.per_collective(),
        "audit_eqns": led.audit_eqns,
        "measured_s": None if measured is None else round(measured, 6),
        "predicted_s": None if pred is None else round(pred, 9),
        "verdict": verdict,
    }
    tel.emit("comm_ledger", app=app, exchange=eng.exchange,
             ndev=led.ndev, ne=led.ne, **comm)
    return comm


def render_report(decomps, fp: Fingerprint) -> str:
    """Human report: fingerprint header + one measured-vs-model table
    per app (the consolidated profile_cliff view)."""
    lines = [
        f"session {fp.session}  platform={fp.platform} "
        f"backend={fp.backend} ndev={fp.ndev}  grade={fp.grade}",
        f"probe: gather {fp.probe['gather_small_ns']:.2f} ns/elem "
        f"(canon {fp.canonical['gather_small_ns']:.2f}, "
        f"deviation {fp.deviation:.2f}x)  pair-dot "
        f"{fp.probe['pair_dot_row_ns']:.0f} ns/row "
        f"(modeled canon {fp.canonical['pair_dot_row_ns']:.0f})",
    ]
    for d in decomps:
        lines.append("")
        lines.append(f"== {d.app} ({d.engine}, exchange={d.exchange}, "
                     f"ne={d.ne}, nv={d.nv}, {d.iters} iters, model "
                     f"x{d.scale:.2f}) ==")
        lines.append(f"{'phase':14s} {'median':>10s} {'mad':>9s} "
                     f"{'model':>10s} {'ratio':>7s}  verdict")
        for p in d.phases:
            pred = ("-" if p.predicted_s is None
                    else f"{p.predicted_s * 1e3:9.2f}ms")
            ratio = "-" if p.ratio is None else f"{p.ratio:6.2f}x"
            lines.append(
                f"{p.phase:14s} {p.median_s * 1e3:8.2f}ms "
                f"{p.mad_s * 1e3:7.2f}ms {pred:>10s} {ratio:>7s}  "
                f"{p.verdict}")
        if d.comm is not None:
            c = d.comm
            wire = ("-" if c["predicted_s"] is None
                    else f"{c['predicted_s'] * 1e3:.3f}ms wire")
            lines.append(
                f"comm: {c['bytes_per_iter']} B/iter over "
                f"{c['messages']} collective(s) [{c['tier']}] "
                f"{wire}  {c['verdict']}")
    return "\n".join(lines)


# ---------------------------------------------------------------------
# pillar 3: persistent perf ledger + carried-debt registry

class PerfLedger:
    """Append-only JSONL of calibrated measurement records.

    One record per line: {"schema", "t", "kind", "session",
    "calibration", ...payload}.  Kinds: "probe" (a calibration run),
    "phase" (an AppDecomposition), "bench" (one bench.py metric
    line), "debt" (a collected carried debt).  Records are never
    rewritten — a degraded session's records stay, labeled by their
    fingerprint, which is the whole point."""

    def __init__(self, path: str = LEDGER_DEFAULT):
        self.path = path

    def append(self, kind: str, payload: dict,
               fingerprint: Fingerprint | None = None) -> dict:
        if kind not in LEDGER_KINDS:
            raise ValueError(f"unknown ledger kind {kind!r} "
                             f"(one of {LEDGER_KINDS})")
        fp = fingerprint or calibrate()
        rec = {"schema": SCHEMA, "t": round(time.time(), 6),
               "kind": kind, "session": fp.session,
               "calibration": fp.digest(), **payload}
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        return rec


def iter_ledger(path: str):
    """Yield (lineno, record|None, error|None) per ledger line."""
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                yield i, None, f"unparseable JSON ({e})"
                continue
            if not isinstance(rec, dict):
                yield i, None, "record is not a JSON object"
                continue
            yield i, rec, None


def validate_ledger(path: str) -> list[str]:
    """Schema audit of a PERFLEDGER.jsonl; returns error strings
    (empty = clean).  Every record must carry schema/kind/session and
    a calibration digest whose grade is a known label — an unlabeled
    sample in the trajectory is exactly what the observatory exists
    to prevent."""
    errs = []
    n = 0
    for i, rec, err in iter_ledger(path):
        if err:
            errs.append(f"line {i}: {err}")
            continue
        n += 1
        if rec.get("schema") != SCHEMA:
            errs.append(f"line {i}: schema={rec.get('schema')!r} "
                        f"(expected {SCHEMA})")
        kind = rec.get("kind")
        if kind not in LEDGER_KINDS:
            errs.append(f"line {i}: unknown kind {kind!r}")
        if not isinstance(rec.get("session"), str) \
                or not rec.get("session"):
            errs.append(f"line {i}: missing session id")
        cal = rec.get("calibration")
        if not isinstance(cal, dict):
            errs.append(f"line {i}: missing calibration digest")
        else:
            if cal.get("grade") not in ("canonical", "degraded",
                                        "uncalibrated"):
                errs.append(f"line {i}: calibration.grade="
                            f"{cal.get('grade')!r} unknown")
            dev = cal.get("deviation")
            if not isinstance(dev, (int, float)) \
                    or isinstance(dev, bool) or not dev == dev \
                    or dev <= 0:
                errs.append(f"line {i}: calibration.deviation="
                            f"{dev!r} must be a finite positive "
                            f"number")
        if kind == "phase" and not isinstance(rec.get("phases"), list):
            errs.append(f"line {i}: phase record without a phases "
                        f"list")
        if kind == "bench" and not isinstance(rec.get("metric"), str):
            errs.append(f"line {i}: bench record without a metric "
                        f"name")
        if kind == "debt" and not isinstance(rec.get("debt"), str):
            errs.append(f"line {i}: debt record without a debt id")
    if n == 0 and not errs:
        errs.append("empty ledger")
    return errs


@dataclasses.dataclass(frozen=True)
class Debt:
    """One owed on-device measurement (ROADMAP "carried hardware
    debts").  ``needs`` gates on the session fingerprint;
    ``auto`` names an implemented probe ``collect_debts`` can run,
    else the debt is listed as manual with its pointer."""
    id: str
    title: str
    pointer: str              # where the owed number is documented
    platform: str = "tpu"     # "tpu" (canonical platforms) | "any"
    min_ndev: int = 1
    auto: str | None = None   # name of an _debt_* probe, or None


DEBTS = (
    Debt("netflix-pair-run",
         "NetFlix colfilter pair run on device (locality-rich "
         "coverage datapoint)", "PERF_NOTES round-8 pointer 1"),
    Debt("pair-dot-row-k-sweep",
         "sweep PAIR_DOT_ROW_K_NS over K (replaces the modeled "
         "5.5 ns/K)", "PERF_NOTES round 8 (modeled, not swept)",
         auto="_debt_pair_dot_sweep"),
    Debt("fused-exchange-ici-ab",
         "ring_reduce_scatter fused min/max owner exchange A/B over "
         "real ICI — price both sides from the ici-bandwidth-probe's "
         "measured bytes/s against the comm ledger's per-mode byte "
         "counts (lux_tpu/comms.py: the ring ships (ndev-1) x "
         "[P/ndev, ntw] rows, the all_to_all (ndev-1)/ndev x "
         "[P, ntw] + an ndev-way local reduce)",
         "PERF_NOTES round-8 pointers; round 19 (comm observatory)",
         min_ndev=2),
    Debt("ici-bandwidth-probe",
         "measured ICI link rate: ppermute-ring + all_to_all payload "
         "sweeps on the loop_bench recipe (observe.calibrate_links); "
         "on a canonical session the figure FEEDS "
         "scalemodel.set_measured_link, replacing the hardcoded "
         "ICI_BYTES_PER_S in every mesh projection",
         "PERF_NOTES round 19 (comm observatory)", platform="any",
         min_ndev=2, auto="_debt_ici_bandwidth_probe"),
    Debt("dcn-bandwidth-probe",
         "measured inter-slice DCN link rate (the 10-100x thinness "
         "ROADMAP item 3 prices blind today): the same link sweep on "
         "a mesh whose axis crosses slice boundaries — gated until a "
         "session actually spans >= 2 slices",
         "PERF_NOTES round 19 (comm observatory); ROADMAP item 3",
         min_ndev=2, auto="_debt_dcn_bandwidth_probe"),
    Debt("watchdog-ab",
         "health watchdog on/off A/B through the tunnel",
         "PERF_NOTES round-9 pointer 1"),
    Debt("pod-direct-probe",
         ">60 s single-execution duration probe (is the ~55 s wall "
         "tunnel-side or pod-side?)", "PERF_NOTES round-8 pointer 4"),
    Debt("elastic-shrink-drill",
         "on-device DEVICE_LOSS shrink drill (remote recompile + "
         "re-shard upload)", "PERF_NOTES round-11 pointer 1",
         min_ndev=2),
    Debt("part-counters-ab",
         "per-part counter variants (round 13, lux_tpu/tracing.py "
         "era) on/off A/B through the tunnel — CPU A/B is within "
         "noise; the on-device all_gather cost is unmeasured",
         "PERF_NOTES round 13", min_ndev=2),
    Debt("paged-gather-ab",
         "on-device paged-vs-flat delivered-rate A/B at the pinned "
         "probe shapes (ops/pagegather.py): the modeled "
         "~0.57-2 ns/edge paged rate vs the measured 8.96 flat "
         "gather — the round-15 break-even model "
         "(scalemodel.page_gather_ns) is primitive-derived, not yet "
         "measured end-to-end on device",
         "PERF_NOTES round 15 (paged gather)",
         auto="_debt_paged_gather_ab"),
    Debt("reorder-fill-ab",
         "page-aware reorder fill A/B (round 16, lux_tpu/reorder.py "
         "+ native/reorder.cc): measured page_fill none vs "
         "native/hillclimb on the locality-rich community shape plus "
         "the modeled delivered ns/edge both ways — the fill side is "
         "HOST-measured (the probe runs anywhere); the on-device "
         "delivered-GTEPS confirmation rides `bench.py -config "
         "gather-ab -shape community -reorder hillclimb` on a live "
         "tunnel", "PERF_NOTES round 16 (locality harvest)",
         platform="any", auto="_debt_reorder_fill_ab"),
    Debt("pagemajor-route-ab",
         "page-major routed delivery A/B on a real mesh (round 16, "
         "ops/pagegather.pagemajor_owner_deliver): the modeled "
         "full-fill gather rows + all_to_all row routing + "
         "virtual-row reduce (scalemodel.pagemajor_gather_ns / "
         "pagemajor_route_ns) vs the owner scan and the plain paged "
         "path — the split constants (VROW_REDUCE_NS, the ICI row "
         "rate) are primitive-derived, not yet measured end-to-end",
         "PERF_NOTES round 16 (page-major routing)", min_ndev=2),
    Debt("serve-slo-on-device",
         "bench.py -config serve-slo (open-loop Poisson load vs the "
         "continuous-batching Server, scripts/loadgen.py) on a live "
         "tunnel: the latency-vs-offered-rate curve, the saturation "
         "knee and the SLO good fraction are CPU-mesh-measured only; "
         "on-device per-query latency (and the knee's position vs "
         "the ~9/B ns/edge amortization) is unmeasured",
         "PERF_NOTES round 17 (serving observability)"),
    Debt("serve-chaos-on-device",
         "bench.py -config serve-chaos (replicated FleetServer under "
         "open-loop load with a ReplicaKillPlan armed, "
         "lux_tpu/fleet.py) on a live tunnel: the kill-under-load "
         "drill — detect -> re-dispatch -> first retired answer "
         "failover cost, the SLO burn through a real replica loss, "
         "and the brownout shed fraction at the saturation knee are "
         "CPU-mesh-measured only (PERF_NOTES round 18); on-device "
         "the failover also pays remote recompile/placement for the "
         "survivor's refilled columns, which nothing has measured",
         "PERF_NOTES round 18 (serving resilience)"),
    Debt("batch-sweep-on-device",
         "bench.py -config batch-sweep (B in {1,8,64} k-source SSSP "
         "+ personalized PageRank) on a live tunnel: the modeled "
         "~9/B per-query amortization (scalemodel.per_query_edge_ns, "
         "BATCH_LANE_NS wide-row lane rate) is CPU-A/B'd only; the "
         "serve refill path's host column scatter also wants a "
         "device-side scatter once measured",
         "PERF_NOTES round 14 (query batching)"),
    Debt("live-mutation-on-device",
         "bench.py -config serve-live (live-graph serving: mutation "
         "stream + delta-relax boundaries + epoch-keyed cache + "
         "compaction, lux_tpu/livegraph.py) on a live tunnel: the "
         "per-boundary delta-relax cost (modeled "
         "count x GATHER_SMALL_NS, the compact_economics drag term), "
         "the WAL fsync cadence vs the tunnel wall, and the "
         "compaction pause under real traffic are CPU-measured only "
         "(PERF_NOTES round 20); the incremental-vs-full "
         "revalidation sweep (scripts/sweep_live.py) also wants the "
         "on-device crossover point",
         "PERF_NOTES round 20 (live graphs)"),
    Debt("live-deletion-on-device",
         "the anti-monotone re-seed (lux_tpu/livegraph.py "
         "_revalidate_anti) computes the deletion cone — forward "
         "reachability from every pending anti op's destination — "
         "on the HOST and re-places the re-seeded state; the "
         "deletion sweep (scripts/sweep_live.py -mode delete, "
         "PERF_NOTES round 21) measured that machinery 3-12x "
         "SLOWER than full recompute at CPU scales because RMAT "
         "cones reach 30-70% of the graph from one deleted "
         "destination, so the cone cap's full-recompute fallback "
         "is doing the serving; a device-side cone (frontier BFS "
         "inside one jit) + in-place re-seed is the open lever, "
         "and the crossover wants measuring through the tunnel",
         "PERF_NOTES round 21 (mutation algebra)"),
    Debt("hbm-watermark-on-device",
         "the round-22 memory observatory's MEASURED leg "
         "(lux_tpu/memwatch.py): every CPU/tunnel sample wears grade "
         "'modeled' because no visible backend exposes "
         "device.memory_stats(); on a session that does, run one "
         "BASELINE ledger config, read the real per-device "
         "peak_bytes_in_use watermark and verdict it against the "
         "unified byte ledger — the first measured-grade "
         "watermark-vs-ledger drift datapoint (and the XLA "
         "temp/padding overhead figure the modeled tolerance only "
         "bounds)",
         "PERF_NOTES round 22 (memory observatory)", platform="tpu",
         auto="_debt_hbm_watermark"),
    Debt("mxu-core-ab",
         "on-device MXU-vs-VPU compare-reduce A/B at the pinned "
         "probe shapes (round 23, ops/tiled.py): the one-hot "
         "contraction sum + the bit-serial tournament max vs the "
         "fused VPU masked reduce at a wide=8 payload — the "
         "scalemodel constants behind use_mxu='auto' and the bench "
         "mxu-ab pair (ONEHOT_TILE_NS, MXU_TILE_NS) are "
         "primitive-derived, and a CPU einsum says nothing about "
         "the systolic array; the measured per-row step-change and "
         "the sum-vs-tournament gap both want a live MXU",
         "PERF_NOTES round 23 (MXU compute core)", platform="tpu",
         auto="_debt_mxu_core_ab"),
)


def match_debts(fp: Fingerprint):
    """Debts this session's topology could collect."""
    out = []
    for d in DEBTS:
        if d.platform == "tpu" and fp.platform not in CANONICAL_PLATFORMS:
            continue
        if fp.ndev < d.min_ndev:
            continue
        out.append(d)
    return out


def _debt_pair_dot_sweep(fp: Fingerprint, clock=time.perf_counter):
    """The PAIR_DOT_ROW_K_NS sweep: the pair-dot probe across K,
    ns/row each — on a canonical platform this replaces the modeled
    5.5 ns/K constant (PERF_NOTES round 8)."""
    sweep = {}
    for k in (1, 4, 8, 16, 20, 32):
        samples, _ = loop_bench(_dot_probe_step, _dot_probe_carry(k),
                                PROBE_LOOP_K, repeats=3, clock=clock)
        m, mad = median_mad(samples)
        sweep[str(k)] = {
            "row_ns": round(m / PROBE_DOT_ROWS * 1e9, 3),
            "mad_ns": round(mad / PROBE_DOT_ROWS * 1e9, 3)}
    return {"debt": "pair-dot-row-k-sweep", "rows": PROBE_DOT_ROWS,
            "sweep": sweep}


def _debt_paged_gather_ab(fp: Fingerprint, clock=time.perf_counter):
    """Paged-vs-flat A/B at the pinned probe shapes: the same
    PROBE_PAGE_ROWS x 128 delivered edges served by (a) the flat
    per-edge gather and (b) the paged row-fetch + lane shuffle —
    ns/edge for both plus the speedup, the number the round-15
    break-even model owes from a live device."""
    import jax.numpy as jnp

    import jax

    from lux_tpu.ops.tiled import chunk_partials

    edges = PROBE_PAGE_ROWS * 128
    rng = np.random.default_rng(3)
    flat_table = jnp.asarray(
        rng.random(PROBE_PAGE_TABLE * 128, np.float32))
    idx = jnp.asarray(rng.integers(
        0, PROBE_PAGE_TABLE * 128,
        (PROBE_PAGE_ROWS, 128)).astype(np.int32))
    rel = jnp.asarray(rng.integers(
        0, 128, (PROBE_PAGE_ROWS, 128)).astype(np.int8))

    def flat_step(carry):
        # the flat side runs the SAME downstream compare-reduce, so
        # the A/B isolates exactly the delivery-stage swap
        t, i, r = carry
        vals = jax.lax.optimization_barrier(jnp.take(t, i, axis=0))
        sv = jnp.sum(chunk_partials(vals, r, 128, "sum"))
        return sv, (t + sv * 1e-30, i, r)

    flat_s, _ = loop_bench(flat_step, (flat_table, idx, rel),
                           PROBE_LOOP_K, repeats=3, clock=clock)
    page_s, _ = loop_bench(_page_probe_step, _page_probe_carry(),
                           PROBE_LOOP_K, repeats=3, clock=clock)
    f_m, f_mad = median_mad(flat_s)
    p_m, p_mad = median_mad(page_s)
    flat_ns = f_m / edges * 1e9
    paged_ns = p_m / edges * 1e9
    return {"debt": "paged-gather-ab", "edges": edges,
            "flat_ns_per_edge": round(flat_ns, 4),
            "flat_mad_ns": round(f_mad / edges * 1e9, 4),
            "paged_ns_per_edge": round(paged_ns, 4),
            "paged_mad_ns": round(p_mad / edges * 1e9, 4),
            "speedup": round(flat_ns / max(paged_ns, 1e-12), 3),
            "method": _page_resolve_method()}


def _debt_reorder_fill_ab(fp: Fingerprint, clock=time.perf_counter):
    """The locality-harvest fill A/B (round 16): build the scrambled
    community shape, measure the plan builder's page_fill under
    none / native / hillclimb reorders (HOST numpy — the objective
    is device-free by construction) and record the modeled delivered
    ns/edge each implies (scalemodel.page_gather_ns), plus what
    ``gather="auto"`` resolves to.  The on-device GTEPS confirmation
    is the gather-ab bench family; this probe pins the fill trail a
    session can always collect."""
    from lux_tpu.convert import community_graph
    from lux_tpu.graph import ShardedGraph
    from lux_tpu.ops.pagegather import plan_paged_stats, resolve_gather
    from lux_tpu.reorder import page_reorder
    from lux_tpu.scalemodel import page_gather_ns

    g = community_graph(scale=14, edge_factor=8, community_scale=8,
                        seed=0)
    out = {"debt": "reorder-fill-ab", "shape": "community14x8",
           "ne": int(g.ne), "orders": {}}
    for method in ("none", "native", "hillclimb"):
        t0 = clock()
        g2, _perm, rep = page_reorder(g, method=method)
        sg = ShardedGraph.build(g2, 1, vpad_align=128)
        st = plan_paged_stats(sg)
        out["orders"][method] = {
            "page_fill": round(float(st["padded_fill"]), 3),
            "page_ratio": round(float(st["page_ratio"]), 4),
            "modeled_ns_per_edge": round(page_gather_ns(
                st["page_ratio"], st["padded_fill"]), 3),
            "auto_resolves": resolve_gather(
                "auto", st, 4 * sg.num_parts * sg.vpad),
            "reorder_s": round(clock() - t0, 2)}
    return out


def _debt_mxu_core_ab(fp: Fingerprint, clock=time.perf_counter):
    """The round-23 MXU A/B at the pinned probe shapes: the SAME
    [rows, 128, 8] wide payload reduced by (a) the fused VPU masked
    reduce and (b) the MXU path — one-hot contraction for sum, the
    bit-serial tournament for max — ns per chunk row for both plus
    the speedup, next to the scalemodel rates the bench mxu-ab pair
    is read against.  Runs on any backend (the CPU figures are the
    honest-negative baseline; only a tunnel session prices the
    systolic array, hence platform='tpu' on the debt)."""
    import jax.numpy as jnp

    from lux_tpu.ops.tiled import chunk_partials
    from lux_tpu.scalemodel import mxu_reduce_row_ns, vpu_reduce_row_ns

    rows, wide = PROBE_PAGE_ROWS, 8
    rng = np.random.default_rng(23)
    vals = jnp.asarray(rng.random((rows, 128, wide), np.float32))
    rel = jnp.asarray(rng.integers(0, 128, (rows, 128)).astype(np.int8))

    out = {"debt": "mxu-core-ab", "rows": rows, "wide": wide,
           "kinds": {}}
    for kind in ("sum", "max"):
        rec = {}
        for label, um in (("vpu", False), ("mxu", True)):
            def step(carry, _um=um, _kind=kind):
                v, r = carry
                s = jnp.sum(chunk_partials(v, r, 128, _kind,
                                           use_mxu=_um))
                return s, (v + s * 1e-30, r)

            samples, _ = loop_bench(step, (vals, rel), PROBE_LOOP_K,
                                    repeats=3, clock=clock)
            m, mad = median_mad(samples)
            rec[f"{label}_row_ns"] = round(m / rows * 1e9, 3)
            rec[f"{label}_mad_ns"] = round(mad / rows * 1e9, 3)
        rec["speedup"] = round(
            rec["vpu_row_ns"] / max(rec["mxu_row_ns"], 1e-12), 3)
        rec["modeled_vpu_row_ns"] = round(vpu_reduce_row_ns(wide), 2)
        rec["modeled_mxu_row_ns"] = round(
            mxu_reduce_row_ns(wide, kind), 2)
        out["kinds"][kind] = rec
    return out


def collect_debts(fp: Fingerprint, ledger: PerfLedger | None,
                  only=None, clock=time.perf_counter):
    """Run every matched debt with an implemented probe, appending a
    "debt" record per collection; manual debts are returned as
    skipped with their pointer, and a probe returning a STRING is a
    gated probe declining this session (e.g. the DCN probe on a
    single-slice mesh) — skipped with the probe's stated reason, no
    record appended.  Returns (collected records, [(debt_id, reason)
    skipped])."""
    collected, skipped = [], []
    for d in match_debts(fp):
        if only is not None and d.id not in only:
            continue
        if d.auto is None:
            skipped.append((d.id, f"manual: {d.pointer}"))
            continue
        payload = globals()[d.auto](fp, clock=clock)
        if isinstance(payload, str):
            skipped.append((d.id, payload))
            continue
        if ledger is not None:
            collected.append(ledger.append("debt", payload, fp))
        else:
            collected.append(payload)
        telemetry.current().emit("debt_collected", debt=d.id)
    return collected, skipped


def _debt_hbm_watermark(fp: Fingerprint, clock=time.perf_counter):
    """The measured-watermark debt: one BASELINE ledger config run
    on a backend that exposes device.memory_stats(), its real peak
    watermark verdicted against the unified byte ledger
    (memwatch.drift_verdict, grade ``measured``).  Declines on
    CPU/tunnel sessions — a modeled number recorded under this debt
    would be exactly the grade-masquerade the observatory's grade
    labels exist to prevent."""
    from lux_tpu import audit, memwatch

    if memwatch.device_memory_stats() is None:
        return ("gated: backend exposes no memory_stats "
                "(CPU/tunnel session) — the measured watermark "
                "needs a real device")
    cfgs = [(label, build) for label, build, led
            in audit.matrix_configs() if led]
    if not cfgs:
        return "gated: no ledger-grade matrix config on this session"
    label, build = cfgs[0]
    eng = build()
    ledger = memwatch.MemoryLedger.for_engine(eng, label)
    trail = memwatch.MemoryTrail(clock=clock)
    jitted, args_thunk = eng.audit_programs()["step"]
    import jax
    out = jitted(*args_thunk())
    jax.block_until_ready(out)
    s = trail.sample(where=f"debt:{label}")
    if s.grade != memwatch.GRADE_MEASURED:
        return "gated: memory_stats vanished between probe and sample"
    v = memwatch.drift_verdict(s.peak_bytes, ledger.total_bytes,
                               grade=s.grade, where=label)
    return {"debt": "hbm-watermark-on-device", "config": label,
            **v}


def _debt_ici_bandwidth_probe(fp: Fingerprint,
                              clock=time.perf_counter):
    """The measured-link debt: run the payload sweeps and record the
    headline rate (fed into scalemodel on canonical platforms by
    calibrate_links itself)."""
    links = calibrate_links(clock=clock)
    if not links:
        return "gated: fewer than 2 devices visible"
    rec = links.get("ici")
    if rec is None:
        # a multi-slice session's all-device mesh measures the DCN
        # bottleneck — recording that under the ICI debt would be the
        # mirror image of the mislabeling the DCN probe gates against
        return ("gated: the all-device mesh axis crosses slices "
                "(tier dcn) — collect dcn-bandwidth-probe instead")
    return {"debt": "ici-bandwidth-probe", **rec}


def _debt_dcn_bandwidth_probe(fp: Fingerprint,
                              clock=time.perf_counter):
    """The inter-slice link debt: only collectable when the visible
    devices actually span >= 2 slices (ROADMAP item 3's pod
    topology); gated otherwise so a single-slice session never
    records an "ICI rate wearing a DCN label"."""
    import jax

    slices = {getattr(d, "slice_index", 0) or 0
              for d in jax.devices()}
    if len(slices) < 2:
        return ("gated: single-slice session — the DCN probe needs "
                "a mesh whose axis crosses slice boundaries")
    links = calibrate_links(clock=clock)
    rec = links.get("dcn")
    if rec is None:
        return "gated: link sweep measured no cross-slice axis"
    return {"debt": "dcn-bandwidth-probe", **rec}


# ---------------------------------------------------------------------
# CLI: python -m lux_tpu.observe

APPS = ("pagerank", "cc", "sssp", "colfilter")


def _build_app_engine(app: str, scale: int, ef: int, num_parts: int,
                      pair_threshold: int | None,
                      gather: str = "flat"):
    from lux_tpu.convert import rmat_graph

    g = rmat_graph(scale=scale, edge_factor=ef, seed=0)
    # per-app graph prep FIRST (cc symmetrizes, colfilter weights),
    # then one relabel of the graph that will actually run
    if app == "cc":
        from lux_tpu.apps import components
        from lux_tpu.graph import Graph
        s, dst = components.symmetrize(*g.edge_arrays())
        g = Graph.from_edges(s, dst, g.nv)
    elif app == "colfilter":
        rng = np.random.default_rng(1)
        g.weights = rng.integers(1, 6, size=g.ne).astype(np.int32)
    elif app not in ("pagerank", "sssp"):
        raise ValueError(f"unknown app {app!r}")
    if pair_threshold is not None:
        from lux_tpu.graph import pair_relabel
        g, _perm, starts = pair_relabel(g, num_parts,
                                        pair_threshold=pair_threshold)
    else:
        starts = None
    kw = dict(num_parts=num_parts, pair_threshold=pair_threshold,
              starts=starts, gather=gather)
    if app == "pagerank":
        from lux_tpu.apps import pagerank
        return pagerank.build_engine(g, **kw)
    if app == "cc":
        from lux_tpu.apps import components
        return components.build_engine(g, **kw)
    if app == "sssp":
        from lux_tpu.apps import sssp
        return sssp.build_engine(g, start_vertex=0, **kw)
    from lux_tpu.apps import colfilter
    return colfilter.build_engine(g, **kw)


def main(argv=None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m lux_tpu.observe",
        description="calibrated phase-decomposition report: session "
                    "probe, per-app measured-vs-scalemodel phase "
                    "costs with drift verdicts, perf-ledger append")
    ap.add_argument("-scale", type=int, default=12,
                    help="RMAT scale of the probe graphs (default 12 "
                         "— attribution reads relative weights, not "
                         "GTEPS, so small graphs suffice on CPU)")
    ap.add_argument("-ef", type=int, default=8, help="edges/vertex")
    ap.add_argument("-np", type=int, default=1, help="partitions")
    ap.add_argument("-pair", type=int, default=None, metavar="T",
                    help="pair-lane threshold (with degree relabel)")
    ap.add_argument("-gather", default="flat",
                    choices=["flat", "paged", "pagemajor", "auto"],
                    help="state-table delivery: 'paged' runs the "
                         "page-binned two-level gather "
                         "(ops/pagegather.py), 'pagemajor' the "
                         "full-row page-major layout (round 16), "
                         "'auto' arbitrates by the scalemodel "
                         "break-even on the plan's measured "
                         "unique-page ratio / fills")
    ap.add_argument("-iters", type=int, default=3,
                    help="measured iterations per phase (median + "
                         "MAD)")
    ap.add_argument("-apps", nargs="+", default=list(APPS),
                    choices=APPS, metavar="APP",
                    help=f"subset of {', '.join(APPS)}")
    ap.add_argument("-events", default=None, metavar="FILE",
                    help="append telemetry events as JSONL")
    ap.add_argument("-ledger", default=LEDGER_DEFAULT, metavar="FILE",
                    help=f"perf ledger path (default "
                         f"{LEDGER_DEFAULT})")
    ap.add_argument("-no-ledger", action="store_true",
                    dest="no_ledger", help="do not append the ledger")
    ap.add_argument("-debts", action="store_true",
                    help="list carried debts matched by this "
                         "session's topology and exit")
    ap.add_argument("-collect-debts", action="store_true",
                    dest="collect_debts",
                    help="run the matched debts with implemented "
                         "probes and append their records")
    args = ap.parse_args(argv)

    events = telemetry.EventLog(args.events) if args.events else None
    ledger = None if args.no_ledger else PerfLedger(args.ledger)
    with telemetry.use(events=events):
        fp = calibrate()
        if fp.grade == "degraded":
            print(f"# WARNING: degraded session — gather probe "
                  f"{fp.deviation:.2f}x off canonical; samples will "
                  f"be labeled, not trusted", file=sys.stderr)
        # the probe record lands in the ledger only when the command
        # MEASURES something (report or debt collection) — a pure
        # -debts listing is read-only
        if ledger is not None and not (args.debts
                                       and not args.collect_debts):
            ledger.append("probe", {"probe": fp.probe}, fp)

        if args.debts or args.collect_debts:
            matched = match_debts(fp)
            if not matched:
                print(f"no carried debts match this session "
                      f"(platform={fp.platform}, ndev={fp.ndev})")
            for d in matched:
                auto = f"auto ({d.auto})" if d.auto else "manual"
                print(f"debt {d.id}: {d.title} [{auto}; {d.pointer}]")
            if args.collect_debts:
                collected, skipped = collect_debts(fp, ledger)
                for rec in collected:
                    print(f"collected {rec['debt']}: "
                          f"{json.dumps(rec.get('sweep', rec))}")
                for did, reason in skipped:
                    print(f"skipped {did}: {reason}")
            if events is not None:
                events.close()
            return 0

        decomps = []
        for app in args.apps:
            if args.gather == "pagemajor" and app == "colfilter":
                # typed engine refusal (K-dim programs keep 'paged');
                # skip loudly instead of failing the whole report
                print(f"# skipping {app}: gather='pagemajor' does "
                      f"not serve K-dim (SDDMM) programs")
                continue
            eng = _build_app_engine(app, args.scale, args.ef, args.np,
                                    args.pair, gather=args.gather)
            d = decompose(eng, app, iters=args.iters, fingerprint=fp)
            decomps.append(d)
            if ledger is not None:
                ledger.append("phase", d.as_dict(), fp)
        print(render_report(decomps, fp))
    if events is not None:
        events.close()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
