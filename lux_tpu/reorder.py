"""Page-aware vertex reordering: the locality harvest's host half.

The paged gather (ops/pagegather.py) prices a delivered edge at
~1.6 ns ONLY when edges sharing a (dst tile, src page) cluster; the
plan builder measures exactly that objective (``plan_paged_stats``:
``padded_fill``/``page_ratio``, the inputs of ``gather="auto"``'s
break-even).  This module turns the objective around: candidate
vertex orders are generated (degree sort; the native clustering BFS,
lux_tpu/native/reorder.cc, both seed polarities) and SCORED directly
against the plan builder's measured fill — no device, pure host — and
the winner is refined by a hill-climb whose move is the
dominant-destination-tile regroup (re-pack source pages so vertices
feeding the same destination tile share pages), each pass accepted
only if the measured ``padded_fill`` improves.

Reference anchor: Lux chooses edge layouts matched to its memory
hierarchy at load time (reference README.md:33-38 scaling discussion;
Jia et al., PVLDB 2017); the microbenchmark-driven objective is the
IPU-dissection method (PAPERS.md).  The permutation is persisted as a
``.perm`` sidecar beside the .lux file (lux_tpu/format.py), applied
at load by ``Graph.from_file(reorder=...)``.
"""

from __future__ import annotations

import numpy as np

from lux_tpu.graph import Graph, ShardedGraph

W = 128

METHODS = ("none", "degree", "native", "hillclimb")


def apply_perm(g: Graph, perm: np.ndarray) -> Graph:
    """Relabel ``g`` by ``perm`` (``perm[new] = old``, the
    degree_relabel direction).  Edge weights ride along."""
    perm = np.asarray(perm, np.int64)
    if perm.shape != (g.nv,) or not np.array_equal(
            np.sort(perm), np.arange(g.nv)):
        raise ValueError(f"perm must be a bijection of [0, {g.nv})")
    rank = np.empty(g.nv, np.int64)
    rank[perm] = np.arange(g.nv)
    src, dst = g.edge_arrays()
    return Graph.from_edges(rank[src], rank[dst], g.nv,
                            weights=g.weights)


def page_fill_stats(g: Graph, num_parts: int = 1,
                    exchange: str = "gather",
                    pagemajor: bool = False) -> dict:
    """The plan builder's measured objective for ``g`` under the
    CURRENT vertex order: build the 128-aligned sharded layout and run
    the counting pass only (ops/pagegather.plan_paged_stats — none of
    the [P, Rp, 128] plan assembly), returning its stats dict.  This
    is what the hill-climb maximizes (``padded_fill``) and what
    ``gather="auto"`` resolves from."""
    from lux_tpu.ops.pagegather import plan_paged_stats

    sg = ShardedGraph.build(g, num_parts, vpad_align=128)
    return plan_paged_stats(sg, exchange=exchange, pagemajor=pagemajor)


def _dominant_tile_regroup(g: Graph) -> np.ndarray:
    """One hill-climb move, as a relative permutation of the CURRENT
    order: key every vertex by the destination tile receiving most of
    its out-edges (ties to the smaller tile; sinks keep their
    position-derived key) and stable-sort — sources feeding the same
    tile become page-mates, which is the quantity the (tile, page)
    bins measure.  O(ne log ne) host numpy + one fused radix sort."""
    from lux_tpu import native

    src, dst = g.edge_arrays()
    n_tiles = -(-g.nv // W)
    key = src * np.int64(n_tiles) + dst // W
    native.sort_kv(key, ())
    newg = np.ones(len(key), bool)
    if len(key):
        newg[1:] = key[1:] != key[:-1]
    b = np.nonzero(newg)[0]
    cnt = np.diff(np.concatenate((b, [len(key)])))
    uk = key[b]
    u_src = uk // np.int64(n_tiles)
    u_tile = uk % np.int64(n_tiles)
    # per source, the tile with the max count (stable ties -> smaller
    # tile): sort groups by (src, -cnt, tile) and keep each first
    order = np.lexsort((u_tile, -cnt, u_src))
    first = np.ones(len(order), bool)
    if len(order):
        first[1:] = u_src[order][1:] != u_src[order][:-1]
    dom = np.full(g.nv, -1, np.int64)
    dom[u_src[order][first]] = u_tile[order][first]
    # sinks (no out-edges) keep their current tile as the key, so the
    # regroup never scatters an already-placed page of sinks
    no_out = dom < 0
    dom[no_out] = np.nonzero(no_out)[0] // W
    return np.argsort(dom, kind="stable")


def page_reorder(g: Graph, method: str = "hillclimb",
                 num_parts: int = 1, exchange: str = "gather",
                 passes: int = 8, verbose: bool = False):
    """Reorder ``g``'s vertices for page locality.

    method:
      none       identity (the report still measures the baseline)
      degree     descending total-degree sort (graph.degree_relabel's
                 order — the round-15 bench preprocessing)
      native     the native clustering passes (native/reorder.cc:
                 label-propagation communities + hub-first BFS), the
                 best BY MEASURED FILL
      hillclimb  all of the above as candidates, then
                 dominant-tile-regroup refinement passes, each
                 accepted only if the measured ``padded_fill`` rises

    Returns ``(g2, perm, report)`` with ``perm[new] = old`` mapping
    the returned graph's ids back to ``g``'s, and ``report`` the
    per-candidate measured stats (JSON-serializable: the inspection
    trail scripts/pair_fill_hist.py renders).
    """
    from lux_tpu import native

    if method not in METHODS:
        raise ValueError(f"unknown reorder method {method!r} "
                         f"(one of {', '.join(METHODS)})")

    def score(g2):
        return page_fill_stats(g2, num_parts, exchange)

    base = score(g)
    report = {"method": method, "num_parts": num_parts,
              "exchange": exchange,
              "candidates": {"none": _digest(base)}}
    identity = np.arange(g.nv, dtype=np.int64)
    if method == "none":
        return g, identity, report

    cands: list[tuple[str, np.ndarray]] = []
    deg = (np.bincount(g.col_idx, minlength=g.nv).astype(np.int64)
           + g.in_degrees())
    cands.append(("degree", np.argsort(-deg, kind="stable")))
    if method in ("native", "hillclimb"):
        src, dst = g.edge_arrays()
        for tag, m in (("native-communities", "communities"),
                       ("native-hubs", "hubs")):
            cands.append((tag, native.reorder_cluster(
                src, dst, g.nv, mode=m).astype(np.int64)))
    if method == "degree":
        cands = cands[:1]

    best = (g, identity, base)
    for tag, perm in cands:
        g2 = apply_perm(g, perm)
        st = score(g2)
        report["candidates"][tag] = _digest(st)
        if verbose:
            print(f"# reorder {tag}: padded_fill "
                  f"{st['padded_fill']:.2f}", flush=True)
        if st["padded_fill"] > best[2]["padded_fill"]:
            best = (g2, perm, st)

    if method == "hillclimb":
        g2, perm, st = best
        for i in range(passes):
            rel = _dominant_tile_regroup(g2)
            cand_perm = perm[rel]
            g3 = apply_perm(g, cand_perm)
            st3 = score(g3)
            report["candidates"][f"regroup{i}"] = _digest(st3)
            if verbose:
                print(f"# reorder regroup{i}: padded_fill "
                      f"{st3['padded_fill']:.2f}", flush=True)
            if st3["padded_fill"] <= st["padded_fill"]:
                break                       # hill-climb: accept only up
            g2, perm, st = g3, cand_perm, st3
        best = (g2, perm, st)

    g2, perm, st = best
    report["chosen_fill"] = round(float(st["padded_fill"]), 3)
    report["baseline_fill"] = round(float(base["padded_fill"]), 3)
    report["chosen"] = _digest(st)
    return g2, perm, report


def _digest(stats: dict) -> dict:
    return {"fill": round(float(stats["fill"]), 3),
            "padded_fill": round(float(stats["padded_fill"]), 3),
            "page_ratio": round(float(stats["page_ratio"]), 4),
            "rows": int(stats["rows"])}
