"""Multi-query serving front-end: request batching + continuous refill.

ROADMAP item 2's front-end: production graph services answer BATCHES
of queries (k-source shortest paths, personalized PageRank with
per-user reset vectors, seeded reachability), and the engines now
carry a query-batch axis ``[vpad, B]`` so ONE state-table gather
serves every query per iteration (engine/program.py ``batch``;
delivered cost ~9/B ns/edge/query, PERF_NOTES "query batching").
This module is the continuous-batching layer on top — the LLM-serving
idiom applied to graph queries, on the segmented/telemetry substrate
PRs 1-8 built:

- a **request queue** (``Server.submit`` / ``BatchCollector``): each
  request is one query (a source vertex, or a reset distribution for
  personalized PageRank); the collector takes up to B queries, or
  whatever has arrived when the collection deadline expires.
- a **BatchRunner** per query kind holding ONE batched engine with a
  fixed column count B.  Queries occupy columns; free columns are
  IDLE (push: all-inactive, contributing the reduce identity through
  the ordinary pre-gather mask; pull: a converged fixed point whose
  updates are no-ops) — the retired-column identity rule
  (ARCHITECTURE.md "Query batching & serving").
- segments run on the EXISTING drivers: push kinds converge through
  ``segmented.converge_segments`` and pull kinds through
  ``segmented.run_segments``, with the continuous-batching refill
  implemented as the drivers' documented ``on_segment`` hook — so
  duration budgeting, telemetry segment events, iter-stats counters
  and the health watchdog all compose unchanged.
- at each segment boundary the hook RETIRES converged columns (push:
  the column's frontier is empty; pull: the column's residual fell
  under ``tol``), scatters their answers into per-query
  :class:`Response` objects, and REFILLS the freed columns from the
  queue (pull refills also swap the column's reset vector in place
  via ``PullEngine.update_program_arrays`` — no recompile).
- per-query telemetry: ``query_enqueue`` / ``query_start`` /
  ``query_done`` events (latency, wait, iterations, segments) plus a
  ``serve_refill`` event per boundary — rendered and validated by
  scripts/events_summary.py.
- streaming SLO metrics (round 17, lux_tpu/metrics.py): every Server
  owns a metrics Registry (``metrics=`` to share or ``metrics=False``
  to disable — the overhead-A/B switch) fed HOST-side at segment
  boundaries only (the hot-path-metrics lint contract): queue depth
  and collect wait-time on ``BatchCollector.collect``, batch
  occupancy / refill and segment counters per ``BatchRunner``
  boundary, per-kind latency histograms at retire, and — with
  ``Server(slo_ms={kind: target_ms})`` — per-kind SLO accounting:
  ``serve_slo_good_total`` / ``serve_slo_violation_total`` counters
  plus a rolling burn-rate gauge (violating fraction over the last
  ``SLO_WINDOW`` retirements; ARCHITECTURE.md "Serving metrics &
  SLOs" has the series catalogue).  ``run()`` publishes a
  ``metrics_snapshot`` telemetry event per drain; scripts/loadgen.py
  reads the snapshots back and scripts/events_summary.py cross-audits
  them against the raw ``query_done`` stream.

Costs and debts: the refill path fetches the [nv, B] state at
boundaries that retire or fill columns (host scatter + re-place) —
O(state) per boundary, fine for the CPU mesh and small B; the
device-side column scatter and the on-device batch sweep are carried
debts (lux_tpu/observe.py DEBTS "batch-sweep-on-device").

Smoke: ``python -m lux_tpu.serve`` builds a small random graph,
enqueues 2B mixed queries (sssp + components + pagerank), drains them
through continuous-batching refill, and verifies every per-query
answer against the apps' batched NumPy oracles (exit 1 on any
mismatch).
"""

from __future__ import annotations

import dataclasses
import queue as _queuemod
import threading
import time
from typing import Callable

import numpy as np

DEFAULT_SEG_ITERS = 4
KINDS = ("sssp", "components", "pagerank")

# rolling SLO burn-rate window: the violating fraction over the last
# SLO_WINDOW retirements per kind (a short multi-batch horizon — long
# enough to smooth one batch's retirements, short enough that a burn
# shows within a few boundaries)
SLO_WINDOW = 64

# live-graph delta-drag sampling cadence (round 21): every Nth
# _apply_delta boundary is fenced-timed into the compaction
# scheduler's economics — sparse enough that the fence's host
# round-trip never shows in serving latency, frequent enough that a
# drain leaves the scheduler a measured median
DRAG_SAMPLE_N = 8


@dataclasses.dataclass
class Request:
    """One query: ``source`` for sssp/components (and one-hot
    pagerank); ``reset`` [nv] overrides it for personalized
    pagerank.  ``tenant``/``priority``/``deadline_s`` are the
    serving-tier admission fields (lux_tpu/fleet.py): plain Servers
    ignore them; the fleet dispatcher quotes quotas per tenant,
    collects deadline-priority (PriorityCollector) and sheds against
    the deadline."""
    qid: int
    kind: str
    source: int | None = None
    reset: np.ndarray | None = None
    t_enqueue: float = 0.0
    tenant: str = "default"
    priority: int = 0
    deadline_s: float | None = None
    # live-graph serving (round 20, lux_tpu/livegraph.py): the epoch
    # this query was ADMITTED at — stamped by Server/FleetServer
    # submit from the live view, pinned for the query's whole life
    # (failover re-dispatch included), and audited at answer time
    # (scripts/events_summary.py torn-epoch rule).  None = static
    # graph.
    epoch: int | None = None
    # bypass the answer-cache LOOKUP for this request (retirement
    # still populates).  The fleet's warm queries set it: a warm
    # query served from a sibling replica's cached answer leaves
    # this replica's engines UNCOMPILED, defeating warm's whole
    # contract (lux_tpu/fleet.py FleetServer.warm).
    no_cache: bool = False


@dataclasses.dataclass
class Response:
    qid: int
    kind: str
    source: int | None
    answer: np.ndarray          # [nv] labels / distances / ranks
    iters: int                  # engine iterations while resident
    segments: int               # boundaries the query lived through
    latency_s: float            # enqueue -> retire
    wait_s: float               # enqueue -> column assignment
    converged: bool = True      # False: retired on the segment cap
    epoch: int | None = None    # admission epoch (live graphs)
    cached: bool = False        # served from the epoch-keyed cache


class _Drained(Exception):
    """Raised by the pull hook when the queue is empty and every
    column is idle — the documented ``on_segment`` abort path of
    ``segmented.run_segments``."""


class BatchCollector:
    """Thread-safe request queue + the collect-up-to-B-or-deadline
    batching rule.  ``put`` is called by ``Server.submit`` (any
    thread); ``collect(n, deadline_s)`` returns up to ``n`` requests,
    waiting at most ``deadline_s`` for the FIRST one and then taking
    only what has already arrived (a deadline of 0 never blocks).

    With ``metrics``/``kind`` set (Server wires them), ``put`` and
    ``collect`` keep the ``serve_queue_depth`` gauge current and
    ``collect`` observes each request's queue wait (enqueue ->
    collection) into ``serve_wait_seconds`` — host-side, boundary-
    cadence calls only.  ``replica`` (the fleet, lux_tpu/fleet.py)
    labels the depth GAUGE per replica — N replicas sharing one
    (name, kind) gauge would be last-writer-wins; shared counters
    and histograms merge correctly and stay fleet-wide."""

    def __init__(self, metrics=None, kind: str | None = None,
                 replica: str | None = None):
        self._q: _queuemod.Queue = _queuemod.Queue()
        self.metrics = metrics
        self.kind = kind
        self.replica = replica

    def _labels(self) -> dict:
        if self.replica is None:
            return {"kind": self.kind}
        return {"kind": self.kind, "replica": self.replica}

    def pending_requests(self) -> list:
        """Snapshot of the queued requests WITHOUT consuming them
        (refresh_live's epoch-consistency guard)."""
        with self._q.mutex:
            return list(self._q.queue)

    def _depth(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge("serve_queue_depth",
                               **self._labels()).set(self._q.qsize())

    def put(self, req: Request) -> None:
        self._q.put(req)
        self._depth()

    def __len__(self) -> int:
        return self._q.qsize()

    def collect(self, n: int, deadline_s: float = 0.0) -> list[Request]:
        out: list[Request] = []
        deadline = time.monotonic() + max(0.0, deadline_s)
        while len(out) < n:
            timeout = deadline - time.monotonic()
            try:
                if not out and timeout > 0:
                    out.append(self._q.get(timeout=timeout))
                else:
                    out.append(self._q.get_nowait())
            except _queuemod.Empty:
                break
        if self.metrics is not None:
            self._depth()
            now = time.monotonic()
            wait = self.metrics.histogram("serve_wait_seconds",
                                          kind=self.kind)
            for req in out:
                wait.observe(max(0.0, now - req.t_enqueue))
        return out


class PriorityCollector(BatchCollector):
    """Deadline-priority request queue (the fleet dispatcher's
    admission queue, lux_tpu/fleet.py) replacing the base collector's
    pure FIFO with a PINNED ordering rule:

    - requests collect highest ``priority`` first, FIFO within a
      priority — EXCEPT
    - a request already past HALF its ``deadline_s`` is AGED: aged
      requests outrank every un-aged one (among themselves: earliest
      absolute deadline first, then FIFO).

    Without the aging clause a saturated high-priority stream
    displaces low-priority requests indefinitely; with it a displaced
    request's extra wait is bounded by half its own deadline plus one
    collection round (tests/test_serve.py pins both halves with a
    deterministic injected clock).  ``collect``'s deadline semantics
    match the base class: wait at most ``deadline_s`` for the FIRST
    request, then take only what has already arrived."""

    def __init__(self, metrics=None, kind: str | None = None,
                 replica: str | None = None,
                 now: Callable[[], float] = time.monotonic):
        # deliberately NOT calling super().__init__: the base Queue
        # is replaced wholesale by the condition-guarded list
        # (collection is a SORT, not a pop), and allocating it would
        # leave a dead always-empty queue for any base path to
        # silently read
        self.metrics = metrics
        self.kind = kind
        self.replica = replica
        self.now = now
        self._items: list[Request] = []
        self._cv = threading.Condition()

    def _depth(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge("serve_queue_depth",
                               **self._labels()).set(len(self))

    def put(self, req: Request) -> None:
        with self._cv:
            self._items.append(req)
            self._cv.notify()
        self._depth()

    def __len__(self) -> int:
        with self._cv:
            return len(self._items)

    def pending_requests(self) -> list:
        with self._cv:
            return list(self._items)

    def _key(self, idx: int, req: Request, now: float):
        aged = (req.deadline_s is not None
                and now - req.t_enqueue >= 0.5 * req.deadline_s)
        if aged:
            # aged bucket outranks everything; earliest absolute
            # deadline first so the most endangered request leads
            return (0, req.t_enqueue + req.deadline_s, idx)
        return (1, -int(req.priority), idx)

    def collect(self, n: int, deadline_s: float = 0.0) -> list[Request]:
        deadline = time.monotonic() + max(0.0, deadline_s)
        with self._cv:
            while not self._items:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                self._cv.wait(timeout)
            now = self.now()
            order = sorted(range(len(self._items)),
                           key=lambda i: self._key(i, self._items[i],
                                                   now))
            take = sorted(order[:max(0, n)])
            out = [self._items[i] for i in order[:max(0, n)]]
            for i in reversed(take):
                del self._items[i]
        if self.metrics is not None:
            self._depth()
            t = time.monotonic()
            wait = self.metrics.histogram("serve_wait_seconds",
                                          kind=self.kind)
            for req in out:
                wait.observe(max(0.0, t - req.t_enqueue))
        return out


# epoch-keyed answer cache (round 20, ROADMAP item 5a): a cached
# entry is served only while younger than its kind's TTL; with a
# per-kind SLO configured the TTL is SLO-derived (an answer this much
# older than the latency target the operator cares about is stale by
# that same standard), else unbounded — epoch keys already guarantee
# correctness, the TTL is a freshness policy on top.
CACHE_TTL_SLO_MULT = 50.0
CACHE_MAX_ENTRIES = 4096
# each entry copies a full nv-length answer vector, so an entry-count
# cap alone scales cache memory with GRAPH SIZE (4096 entries at
# rmat21 nv~2M f32 is ~34 GB) — the byte budget is the binding cap on
# big graphs, the entry count on small ones
CACHE_MAX_BYTES = 256 * 1024 * 1024


def _engine_family(kind: str) -> str:
    """The ONE kind-to-family rule (push kinds see base + published
    delta, pull kinds the base generation — livegraph module
    docstring): Server and FleetServer both pin through here, so a
    failover re-dispatch and the original admission can never
    disagree about the epoch."""
    return "pull" if kind == "pagerank" else "push"


def admission_epoch(live, kind: str) -> int | None:
    """READ the epoch a query of ``kind`` would pin (cache sweeps,
    re-stamps).  Admission itself must use ``admit_query`` — a
    separate read + admit would leave a window where a
    mutate+compact folds the just-stamped view away before the
    admission ledger protects it."""
    if live is None:
        return None
    return live.view_epoch(_engine_family(kind))


def _epoch_reproducible(live, req) -> bool:
    """Can the CURRENT generation still serve a queued query pinned
    at ``req.epoch``?  BOTH families replay any epoch in
    [base_epoch, epoch] (round 21): push kinds through the
    per-column delta mask, pull kinds through the base-generation +
    degree-correction step — the delta holds exactly the mutations
    past base_epoch, and admission never pins past a pending
    anti-monotone op (livegraph.view_epoch), so every mutation in
    the pinned window is an append both mechanisms express.
    Anything older was folded away and adoption would serve a torn
    view.  The ONE staleness rule refresh_live (Server and
    FleetServer) checks — comparing against the LATEST view epoch
    instead would wedge the server whenever ingest lands between
    compact() and refresh_live() while a reproducible query sits
    queued (compact refuses on the admission ledger, run() refuses
    on the stale base, refresh_live refuses on the false
    mismatch)."""
    if req.epoch is None:
        return False
    return req.epoch >= int(live.base_epoch)


def admit_query(live, kind: str) -> int | None:
    """ATOMIC admission: take the ledger entry and the epoch stamp
    under one LiveGraph lock acquisition (livegraph.LiveGraph.admit).
    Paired with exactly one ``live.release()`` at retirement/shed."""
    if live is None:
        return None
    return live.admit(_engine_family(kind))


@dataclasses.dataclass
class _CacheEntry:
    answer: np.ndarray
    iters: int
    epoch: int
    t: float


class AnswerCache:
    """Epoch-keyed (kind, source/reset-hash, epoch) -> answer cache
    for the serving front-end (round 20, ROADMAP item 5a).

    The EPOCH is part of the key, so a stale-epoch hit is impossible
    by construction — a query admitted after a mutation carries the
    new epoch and misses (tests pin this: a stale-epoch hit is a
    test failure).  ``sweep`` drops entries whose epoch is no longer
    any kind's live view epoch (invalidation on epoch advance keeps
    the map from accreting dead generations); ``ttl_s`` per kind
    bounds entry age (SLO-aware when built by Server from slo_ms);
    LRU-evicted past ``max_entries`` OR ``max_bytes`` — each entry
    copies a full nv-length answer, so the byte budget is the
    binding cap on big graphs.
    Thread-safe: submit threads look up while the drain thread
    inserts.  Hit/miss Counter metrics are incremented by the
    runners (serve_cache_hit_total / serve_cache_miss_total)."""

    def __init__(self, ttl_s: dict | None = None,
                 max_entries: int = CACHE_MAX_ENTRIES,
                 max_bytes: int = CACHE_MAX_BYTES):
        import collections
        self._d: dict = collections.OrderedDict()
        self._lock = threading.Lock()
        self.ttl_s = dict(ttl_s or {})
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self.bytes = 0              # sum of cached answer nbytes
        self.hits = 0
        self.misses = 0
        # round-22 observatory rule (scripts/lint_lux.py
        # budget-gauge): a consumer with a byte BUDGET must publish a
        # byte GAUGE — a cap nobody can watch approaching is how the
        # cache stayed unpriced through rounds 20-21
        self._gauge = None

    def set_metrics(self, registry, replica: str | None = None):
        """Mirror the exact internal byte ledger into a registry
        gauge (``serve_cache_bytes``); updated inside put/_pop under
        the cache lock, so the gauge can never lag the ledger."""
        labels = {} if replica is None else {"replica": replica}
        self._gauge = (None if registry is None
                       else registry.gauge("serve_cache_bytes",
                                           **labels))
        if self._gauge is not None:
            self._gauge.set(self.bytes)

    def _sync_gauge(self) -> None:
        if self._gauge is not None:
            self._gauge.set(self.bytes)

    def _pop(self, key) -> None:
        """Drop one entry, keeping the byte ledger exact (caller
        holds the lock)."""
        ent = self._d.pop(key)
        self.bytes -= ent.answer.nbytes
        self._sync_gauge()

    @classmethod
    def from_slo(cls, slo_ms: dict | None) -> "AnswerCache":
        """SLO-derived TTLs: an answer older than
        ``CACHE_TTL_SLO_MULT`` x the kind's latency target is stale
        by the operator's own standard.  The ONE construction rule
        behind ``cache=True`` — Server and FleetServer both build
        through here, so the TTL semantics can never desynchronize
        between the single-server and fleet tiers."""
        return cls(ttl_s={k: CACHE_TTL_SLO_MULT * v / 1e3
                          for k, v in (slo_ms or {}).items()})

    @staticmethod
    def query_key(req: Request):
        # memoized per Request: the reset digest hashes a full
        # nv-length vector, and get (lookup) + put (populate) would
        # otherwise both pay it inside the SLO-measured latency
        key = getattr(req, "_cache_key", None)
        if key is not None:
            return key
        if req.reset is not None:
            import hashlib
            buf = np.ascontiguousarray(req.reset,
                                       np.float32).tobytes()
            # 128-bit digest, NOT a 32-bit CRC: two distinct reset
            # vectors colliding would serve each other's answers —
            # a silently WRONG answer (converged, epoch-consistent,
            # invisible to every audit), and at ~77k distinct resets
            # a 32-bit key reaches even birthday odds
            key = ("reset",
                   hashlib.blake2b(buf, digest_size=16).digest(),
                   len(buf))
        else:
            key = ("source", req.source)
        req._cache_key = key
        return key

    def get(self, kind: str, req: Request,
            now: float) -> _CacheEntry | None:
        key = (kind, self.query_key(req), req.epoch or 0)
        ttl = self.ttl_s.get(kind)
        with self._lock:
            ent = self._d.get(key)
            if ent is not None and ttl is not None \
                    and now - ent.t > ttl:
                self._pop(key)       # expired: miss, and forget it
                ent = None
            if ent is None:
                self.misses += 1
                return None
            self._d.move_to_end(key)     # LRU: a hit renews recency
            self.hits += 1
            return ent

    def put(self, kind: str, req: Request, answer: np.ndarray,
            iters: int, epoch: int, now: float) -> None:
        key = (kind, self.query_key(req), epoch or 0)
        ent = _CacheEntry(np.asarray(answer).copy(), int(iters),
                          int(epoch or 0), now)
        with self._lock:
            old = self._d.get(key)
            if old is not None:
                self.bytes -= old.answer.nbytes
            self._d[key] = ent
            self._d.move_to_end(key)     # LRU: replace renews too
            self.bytes += ent.answer.nbytes
            self._sync_gauge()
            while len(self._d) > 1 \
                    and (len(self._d) > self.max_entries
                         or self.bytes > self.max_bytes):
                self._pop(next(iter(self._d)))

    def sweep(self, live_epochs: dict) -> int:
        """Drop entries whose (kind, epoch) is no longer a live view
        epoch — the invalidation-on-epoch-advance leg.  Returns the
        number dropped."""
        with self._lock:
            dead = [k for k in self._d
                    if k[0] in live_epochs
                    and k[2] != (live_epochs[k[0]] or 0)]
            for k in dead:
                self._pop(k)
        return len(dead)

    def hit_fraction(self) -> float | None:
        n = self.hits + self.misses
        return None if n == 0 else self.hits / n


@dataclasses.dataclass
class _Slot:
    req: Request
    t_start: float
    iter_start: int
    segments: int = 0


def _emit(event: str, **fields):
    from lux_tpu import telemetry
    telemetry.current().emit(event, **fields)


class _RunnerBase:
    """Shared slot bookkeeping for one batched engine of width B."""

    def __init__(self, kind: str, B: int, seg_iters: int,
                 max_segments: int, metrics=None,
                 slo_ms: float | None = None, live=None, cache=None):
        self.kind = kind
        self.B = int(B)
        self.seg_iters = int(seg_iters)
        self.max_segments = int(max_segments)
        self.slots: list[_Slot | None] = [None] * self.B
        self.responses: list[Response] = []
        self.metrics = metrics
        self.slo_ms = None if slo_ms is None else float(slo_ms)
        # live-graph serving (round 20, lux_tpu/livegraph.py): the
        # shared LiveGraph (resident queries PIN its generation so a
        # compaction cannot swap the base under them) and the
        # epoch-keyed answer cache (ROADMAP item 5a)
        self.live = live
        self.cache = cache
        # serving-tier hooks (lux_tpu/fleet.py): ``replica`` labels
        # the per-query events with the runner's replica name, and
        # ``on_boundary(runner)`` fires at the TOP of every segment
        # boundary — the fleet's heartbeat-beat + chaos-kill-plan
        # injection point (an exception raised there propagates out
        # of drain() as a mid-drain replica death)
        self.replica: str | None = None
        self.on_boundary: Callable | None = None
        # memory observatory (round 22, lux_tpu/memwatch.py): the
        # boundary sampler rides the SAME hook cadence — O(1) host
        # work per segment boundary, never inside the fused loop
        self.mem = None
        # rolling SLO window: True per retirement = violation
        import collections
        self._slo_window = collections.deque(maxlen=SLO_WINDOW)

    def _rep(self) -> dict:
        return {} if self.replica is None else {"replica": self.replica}

    def _free_cols(self):
        return [c for c, s in enumerate(self.slots) if s is None]

    def _occupied(self):
        return [c for c, s in enumerate(self.slots) if s is not None]

    def _answer_epoch(self, col: int) -> int | None:
        """The epoch the answer in ``col`` was actually computed at —
        runner-specific (push: the column's delta-mask epoch; pull:
        the engine's base-generation epoch).  Audited against the
        admission epoch by scripts/events_summary.py; a divergence is
        a torn read, so this must come from the MECHANISM, never be
        copied from the request."""
        return None

    def _start(self, col: int, req: Request, total_iters: int):
        now = time.monotonic()
        self.slots[col] = _Slot(req=req, t_start=now,
                                iter_start=total_iters)
        if self.live is not None:
            self.live.pin()
        ep = {} if req.epoch is None else {"epoch": req.epoch}
        _emit("query_start", qid=req.qid, query_kind=self.kind,
              col=col,
              wait_s=round(now - req.t_enqueue, 6), **ep,
              **self._rep())

    def _retire(self, col: int, answer: np.ndarray, total_iters: int,
                converged: bool = True):
        slot = self.slots[col]
        answer_epoch = self._answer_epoch(col)
        self.slots[col] = None
        if self.live is not None:
            self.live.unpin()
        now = time.monotonic()
        resp = Response(
            qid=slot.req.qid, kind=self.kind, source=slot.req.source,
            answer=answer, iters=total_iters - slot.iter_start,
            segments=slot.segments,
            latency_s=now - slot.req.t_enqueue,
            wait_s=slot.t_start - slot.req.t_enqueue,
            converged=converged, epoch=slot.req.epoch)
        self.responses.append(resp)
        if self.cache is not None and converged:
            self.cache.put(self.kind, slot.req, answer, resp.iters,
                           (answer_epoch if answer_epoch is not None
                            else slot.req.epoch or 0), now)
        slo = {}
        if self.slo_ms is not None:
            slo_ok = resp.latency_s * 1e3 <= self.slo_ms
            slo = {"slo_ms": self.slo_ms, "slo_ok": slo_ok}
            self._slo_window.append(not slo_ok)
        if self.metrics is not None:
            m = self.metrics
            m.histogram("serve_latency_seconds",
                        kind=self.kind).observe(resp.latency_s)
            m.counter("serve_retired_total", kind=self.kind).inc()
            if not converged:
                m.counter("serve_segment_cap_total",
                          kind=self.kind).inc()
            if self.slo_ms is not None:
                m.counter("serve_slo_good_total" if slo["slo_ok"]
                          else "serve_slo_violation_total",
                          kind=self.kind).inc()
                burn = (sum(self._slo_window)
                        / max(1, len(self._slo_window)))
                m.gauge("serve_slo_burn_rate",
                        kind=self.kind).set(burn)
        ep = {}
        if resp.epoch is not None:
            # answer_epoch comes from the serving MECHANISM (delta
            # mask / engine generation), epoch from admission — the
            # events_summary torn-epoch audit fails any divergence
            ep = {"epoch": resp.epoch,
                  "answer_epoch": (answer_epoch
                                   if answer_epoch is not None
                                   else resp.epoch)}
        _emit("query_done", qid=resp.qid, query_kind=self.kind,
              col=col,
              iters=resp.iters, segments=resp.segments,
              latency_s=round(resp.latency_s, 6),
              wait_s=round(resp.wait_s, 6), converged=converged,
              **ep, **slo, **self._rep())
        return resp

    def _serve_cached(self, req: Request) -> bool:
        """Serve ``req`` straight from the epoch-keyed answer cache
        when possible — no column, no engine dispatch (ROADMAP item
        5a).  The entry's epoch equals the request's admission epoch
        BY KEY, so a hit can never be stale-epoch."""
        if self.cache is None or req.no_cache:
            return False
        now = time.monotonic()
        ent = self.cache.get(self.kind, req, now)
        if self.metrics is not None:
            self.metrics.counter(
                "serve_cache_hit_total" if ent is not None
                else "serve_cache_miss_total", kind=self.kind).inc()
        if ent is None:
            return False
        resp = Response(
            qid=req.qid, kind=self.kind, source=req.source,
            answer=ent.answer.copy(), iters=ent.iters, segments=0,
            latency_s=now - req.t_enqueue,
            wait_s=now - req.t_enqueue, converged=True,
            epoch=req.epoch, cached=True)
        self.responses.append(resp)
        slo = {}
        if self.slo_ms is not None:
            ok = resp.latency_s * 1e3 <= self.slo_ms
            slo = {"slo_ms": self.slo_ms, "slo_ok": ok}
            self._slo_window.append(not ok)
        if self.metrics is not None:
            m = self.metrics
            m.histogram("serve_latency_seconds",
                        kind=self.kind).observe(resp.latency_s)
            m.counter("serve_retired_total", kind=self.kind).inc()
            if self.slo_ms is not None:
                m.counter("serve_slo_good_total" if slo["slo_ok"]
                          else "serve_slo_violation_total",
                          kind=self.kind).inc()
        ep = {} if req.epoch is None else \
            {"epoch": req.epoch, "answer_epoch": ent.epoch}
        _emit("query_done", qid=resp.qid, query_kind=self.kind,
              col=-1, iters=resp.iters, segments=0,
              latency_s=round(resp.latency_s, 6),
              wait_s=round(resp.wait_s, 6), converged=True,
              cached=True, **ep, **slo, **self._rep())
        return True

    def _boundary_metrics(self, retired: int, filled: int,
                          queued: int) -> None:
        """Per-segment-boundary series (host-side by construction —
        the drivers' on_segment hooks are the only callers): batch
        occupancy, segment count, retire/refill rates."""
        if self.metrics is None:
            return
        m = self.metrics
        # counters are SHARED fleet-wide (they sum correctly across
        # replicas); the gauges are per-replica quantities and carry
        # the replica label when one is set — N replicas writing one
        # (name, kind) gauge would be last-writer-wins noise
        m.counter("serve_segments_total", kind=self.kind).inc()
        m.gauge("serve_batch_occupancy", kind=self.kind,
                **self._rep()).set(len(self._occupied()))
        m.gauge("serve_queue_depth", kind=self.kind,
                **self._rep()).set(queued)
        if filled:
            m.counter("serve_refilled_total",
                      kind=self.kind).inc(filled)


class PushBatchRunner(_RunnerBase):
    """Continuous-batching runner for push kinds (sssp /
    components): one batched PushEngine, columns retire when their
    per-query frontier empties, refill rides
    ``converge_segments``'s ``on_segment`` hook."""

    def __init__(self, kind: str, g, B: int, *, num_parts: int = 1,
                 mesh=None, exchange: str = "auto",
                 health: bool = False, weighted: bool = False,
                 seg_iters: int = DEFAULT_SEG_ITERS,
                 max_segments: int = 10_000, metrics=None,
                 slo_ms: float | None = None, live=None, cache=None):
        super().__init__(kind, B, seg_iters, max_segments,
                         metrics=metrics, slo_ms=slo_ms, live=live,
                         cache=cache)
        self.g = g
        # per-column admission epochs (live graphs): the delta-relax
        # step masks each column's delta edges to its OWN epoch, so
        # columns admitted at different epochs share one engine
        # dispatch with snapshot isolation intact
        self._col_epoch = np.zeros(self.B, np.int32)
        # delta-drag sampling cadence (round 21): every DRAG_SAMPLE_N
        # boundaries one _apply_delta is fenced-timed and fed to the
        # scheduler's economics (LiveGraph.record_drag_sample)
        self._delta_n = 0
        self.weighted = bool(weighted and kind == "sssp")
        placeholder = [0] * self.B
        if kind == "sssp":
            from lux_tpu.apps import sssp as app
            self.eng = app.build_engine(
                g, sources=placeholder, num_parts=num_parts,
                mesh=mesh, weighted=self.weighted,
                exchange=exchange, health=health)
            self._inf = (app.DIST_INF if self.weighted
                         else app.HOP_INF)
            self._dtype = np.float32 if self.weighted else np.int32
        elif kind == "components":
            from lux_tpu.apps import components as app
            self.eng = app.build_engine(
                g, sources=placeholder, num_parts=num_parts,
                mesh=mesh, exchange=exchange, health=health)
            self._inf = np.int32(-1)
            self._dtype = np.int32
        else:
            raise ValueError(f"unknown push kind {kind!r}")

    def _col_init(self, req: Request):
        """(label [nv], active [nv]) for a fresh query column."""
        nv = self.g.nv
        s = int(req.source)
        if not 0 <= s < nv:
            raise ValueError(f"query {req.qid}: source {s} out of "
                             f"range [0, {nv})")
        lab = np.full(nv, self._inf, dtype=self._dtype)
        act = np.zeros(nv, dtype=bool)
        lab[s] = s if self.kind == "components" else 0
        act[s] = True
        return lab, act

    def drain(self, collector: BatchCollector,
              deadline_s: float = 0.0) -> list[Response]:
        """Serve until the collector is empty and every column is
        idle; returns the responses retired during this drain."""
        import jax
        import jax.numpy as jnp

        from lux_tpu.segmented import converge_segments

        eng, sg = self.eng, self.eng.sg
        nv, B = self.g.nv, self.B
        n0 = len(self.responses)

        lab_h = np.full((nv, B), self._inf, dtype=self._dtype)
        act_h = np.zeros((nv, B), dtype=bool)
        filled = self._fill(lab_h, act_h, collector, 0, deadline_s)
        if not filled:
            # cache hits may have retired queries without taking a
            # column — they are this drain's responses
            return self.responses[n0:]
        label, active = eng.place(sg.to_padded(lab_h),
                                  sg.to_padded(act_h))

        def hook(label, active, total, cnt):
            if self.on_boundary is not None:
                self.on_boundary(self)
            if self.mem is not None:
                self.mem.sample(where=f"{self.kind}:boundary")
            for s in self.slots:
                if s is not None:
                    s.segments += 1
            if self.live is not None:
                # the live delta-relax step: delta blocks as jit
                # ARGUMENTS, each column masked to its OWN admission
                # epoch (snapshot isolation inside one dispatch).  A
                # column retires only when its frontier is empty AND
                # the delta offered no improvement — i.e. at the
                # fixed point of base + delta@its-epoch.
                label, active = self._apply_delta(label, active)
            counts = np.asarray(jax.device_get(
                jnp.sum(active, axis=tuple(range(active.ndim - 1)))))
            done = [c for c in self._occupied()
                    if counts[c] == 0
                    or self.slots[c].segments >= self.max_segments]
            want_fill = len(collector) > 0 and (
                done or self._free_cols())
            if not done and not want_fill:
                self._boundary_metrics(0, 0, len(collector))
                # the delta step may have changed the device state —
                # hand the updated arrays back to the driver
                return (label, active) if self.live is not None \
                    else None
            lab_h = sg.from_padded(np.asarray(jax.device_get(label)))
            act_h = sg.from_padded(np.asarray(jax.device_get(active)))
            for c in done:
                self._retire(c, lab_h[:, c].copy(), total,
                             converged=bool(counts[c] == 0))
                lab_h[:, c] = self._inf
                act_h[:, c] = False
            n_filled = self._fill(lab_h, act_h, collector, total,
                                  deadline_s)
            _emit("serve_refill", query_kind=self.kind,
                  retired=len(done),
                  filled=n_filled, occupied=len(self._occupied()),
                  queued=len(collector))
            self._boundary_metrics(len(done), n_filled,
                                   len(collector))
            return eng.place(sg.to_padded(lab_h), sg.to_padded(act_h))

        converge_segments(eng, label, active, self.seg_iters,
                          on_segment=hook)
        return self.responses[n0:]

    def _apply_delta(self, label, active):
        """One live delta-relax application (livegraph.delta_step —
        cached per engine inside LiveGraph, shared with revalidate
        and register_audit) on the DEVICE state at a segment
        boundary.  Every ``DRAG_SAMPLE_N``-th application is
        fenced-timed (timing.fence — O(1) bytes, never a full-state
        fetch inside the timed region) and fed to the compaction
        scheduler's economics as a MEASURED per-boundary drag sample
        (LiveGraph.record_drag_sample)."""
        import jax.numpy as jnp

        args = self.live.delta_arrays(self.eng.sg)
        n_slots = int(self.live.count)
        self._delta_n += 1
        sample = (n_slots > 0
                  and self._delta_n % DRAG_SAMPLE_N == 1)
        if sample:
            t0 = time.perf_counter()
        label, active, _imp = self.live.delta_step(self.eng)(
            label, active, *args, jnp.asarray(self._col_epoch))
        if sample:
            from lux_tpu import timing
            timing.fence(label)
            self.live.record_drag_sample(
                time.perf_counter() - t0, n_slots)
        return label, active

    def _answer_epoch(self, col: int) -> int | None:
        if self.live is None:
            return None
        return int(self._col_epoch[col])

    def _fill(self, lab_h, act_h, collector, total_iters,
              deadline_s) -> int:
        free = self._free_cols()
        filled = 0
        first = True
        while free:
            reqs = collector.collect(len(free),
                                     deadline_s if first else 0.0)
            first = False
            if not reqs:
                break
            for req in reqs:
                if self._serve_cached(req):
                    continue     # answered without a column
                col = free.pop(0)
                lab_h[:, col], act_h[:, col] = self._col_init(req)
                self._col_epoch[col] = req.epoch or 0
                self._start(col, req, total_iters)
                filled += 1
        return filled


class PullBatchRunner(_RunnerBase):
    """Continuous-batching runner for personalized PageRank: one
    batched PullEngine; a column retires when its per-query residual
    (max-abs state change over a segment's last iteration, computed
    at the boundary) falls under ``tol``; refill swaps the column's
    reset vector in place (``PullEngine.update_program_arrays``)."""

    def __init__(self, kind: str, g, B: int, *, num_parts: int = 1,
                 mesh=None, exchange: str = "auto",
                 health: bool = False,
                 seg_iters: int = DEFAULT_SEG_ITERS,
                 tol: float = 1e-8, max_segments: int = 500,
                 metrics=None, slo_ms: float | None = None,
                 live=None, cache=None):
        super().__init__(kind, B, seg_iters, max_segments,
                         metrics=metrics, slo_ms=slo_ms, live=live,
                         cache=cache)
        if kind != "pagerank":
            raise ValueError(f"unknown pull kind {kind!r}")
        from lux_tpu.apps import pagerank as app
        self.g = g
        self.app = app
        self.tol = float(tol)
        # live pull serving (round 21): appends change out-degree
        # normalization, which the engine's base iteration cannot
        # see — so each column runs at its OWN admission epoch via
        # the base-generation + correction split: the engine
        # normalizes by the EFFECTIVE degree (base + the column's
        # delta-append out-degree, the ``deg_corr`` extra array) and
        # the boundary hook adds the delta edges' rank mass
        # host-side — together one exact PPR iteration over
        # graph_at(col_epoch).  The correction is per-ITERATION
        # math, so live forces seg_iters to 1 (the hook must run
        # between consecutive iterations, not after a burst).
        self._col_epoch = np.zeros(B, np.int32)
        self.deg_corr = np.zeros((g.nv, B), np.float32)
        if live is not None:
            self.seg_iters = 1
        # idle columns carry the uniform reset's fixed-point-bound
        # trajectory — cheap, and refilled before they matter
        self.resets = np.full((g.nv, B), 1.0 / g.nv, dtype=np.float32)
        self.eng = app.build_engine(
            g, num_parts=num_parts, mesh=mesh, resets=self.resets,
            exchange=exchange, health=health)

    def _col_reset(self, req: Request) -> np.ndarray:
        if req.reset is not None:
            r = np.asarray(req.reset, np.float32)
            if r.shape != (self.g.nv,):
                raise ValueError(
                    f"query {req.qid}: reset must be [nv], got "
                    f"{r.shape}")
            return r
        return self.app.one_hot_resets(self.g.nv,
                                       [int(req.source)])[:, 0]

    def _col_init(self, reset: np.ndarray, col: int) -> np.ndarray:
        # the column's init state normalizes by the same EFFECTIVE
        # degree the engine's apply uses (base + deg_corr) — mixing
        # base-degree init with corrected-degree iteration would
        # start the column off its own trajectory
        deg = np.asarray(self.g.out_degrees, np.float32) \
            + self.deg_corr[:, col]
        return np.where(deg > 0, reset / np.maximum(deg, 1),
                        reset).astype(np.float32)

    def drain(self, collector: BatchCollector,
              deadline_s: float = 0.0) -> list[Response]:
        import jax

        from lux_tpu.segmented import run_segments

        eng, sg = self.eng, self.eng.sg
        B = self.B
        n0 = len(self.responses)

        state_h = sg.from_padded(np.asarray(
            self.eng.program.init(sg)))          # [nv, B]
        if not self._fill(state_h, collector, 0, deadline_s):
            return self.responses[n0:]   # cache hits take no column
        self._push_resets()
        prev = state_h.copy()
        state = eng.place(sg.to_padded(state_h))

        def hook(state, done_iters):
            nonlocal prev
            if self.on_boundary is not None:
                self.on_boundary(self)
            if self.mem is not None:
                self.mem.sample(where=f"{self.kind}:boundary")
            for s in self.slots:
                if s is not None:
                    s.segments += 1
            new = sg.from_padded(np.asarray(jax.device_get(state)))
            corrected = False
            if self.live is not None:
                # the host half of the live pull iteration: add the
                # delta appends' rank mass (the engine already
                # normalized by the effective degree) — new is now
                # one exact PPR iteration of prev over each column's
                # graph_at(col_epoch)
                new, corrected = self._correct(prev, new)
            # per-query convergence: max-abs state change over the
            # WHOLE segment <= tol (an upper bound on any single
            # iteration's residual — strictly conservative)
            res = np.max(np.abs(new - prev), axis=0)
            done = [c for c in self._occupied()
                    if res[c] <= self.tol
                    or self.slots[c].segments >= self.max_segments]
            for c in done:
                self._retire(c, new[:, c].copy(), done_iters,
                             converged=bool(res[c] <= self.tol))
            n_filled = self._fill(new, collector, done_iters,
                                  deadline_s)
            if done or n_filled:
                _emit("serve_refill", query_kind=self.kind,
                      retired=len(done), filled=n_filled,
                      occupied=len(self._occupied()),
                      queued=len(collector))
            self._boundary_metrics(len(done), n_filled,
                                   len(collector))
            if not self._occupied() and not len(collector):
                raise _Drained()
            prev = new
            if n_filled:
                self._push_resets()
                return eng.place(sg.to_padded(new))
            if corrected:
                # the host correction changed the state the next
                # iteration must start from — hand it back even when
                # no column turned over
                return eng.place(sg.to_padded(new))
            return None

        try:
            run_segments(eng, state, np.iinfo(np.int32).max,
                         self.seg_iters, on_segment=hook)
        except _Drained:
            pass
        return self.responses[n0:]

    def _correct(self, prev, new):
        """Host half of the live pull iteration (round 21): the
        engine produced ``apply(acc_base)`` of ``prev`` with
        effective-degree normalization; one exact PPR iteration over
        ``graph_at(col_epoch)`` additionally accumulates ``ALPHA *
        prev[src]`` into each delta-append edge's destination, with
        the SAME normalization (linearity of the divide).  Each
        column masks the delta to its own admission epoch — the
        snapshot-isolation rule the push delta step enforces
        on-device, applied host-side."""
        ds, dd, _dw, de = self.live.append_deltas()
        if not len(ds):
            return new, False
        mask = de[:, None] <= self._col_epoch[None, :]
        if not mask.any():
            return new, False
        acc = np.zeros_like(new)
        np.add.at(acc, dd, prev[ds] * mask)
        deg_eff = np.asarray(self.g.out_degrees,
                             np.float32)[:, None] + self.deg_corr
        new = new + self.app.ALPHA * acc / np.maximum(deg_eff, 1.0)
        return new.astype(np.float32), True

    def _answer_epoch(self, col: int) -> int | None:
        if self.live is None:
            return None
        return int(self._col_epoch[col])

    def _push_resets(self):
        kw = {"reset": self.eng.sg.to_padded(self.resets)}
        if self.live is not None:
            kw["deg_corr"] = self.eng.sg.to_padded(self.deg_corr)
        self.eng.update_program_arrays(**kw)

    def _fill(self, state_h, collector, total_iters,
              deadline_s) -> int:
        free = self._free_cols()
        filled = 0
        first = True
        while free:
            reqs = collector.collect(len(free),
                                     deadline_s if first else 0.0)
            first = False
            if not reqs:
                break
            for req in reqs:
                if self._serve_cached(req):
                    continue     # answered without a column
                col = free.pop(0)
                reset = self._col_reset(req)
                self.resets[:, col] = reset
                if self.live is not None:
                    # pin the column's epoch and materialize its
                    # delta-append out-degree correction — fixed for
                    # the column's residence (later appends carry
                    # later epochs, anti ops cap admission below
                    # themselves, so nothing admitted can change it)
                    e = int(req.epoch or 0)
                    self._col_epoch[col] = e
                    self.deg_corr[:, col] = 0.0
                    ds, _dd, _dw, de = self.live.append_deltas()
                    np.add.at(self.deg_corr[:, col], ds[de <= e],
                              1.0)
                state_h[:, col] = self._col_init(reset, col)
                self._start(col, req, total_iters)
                filled += 1
        return filled


class Server:
    """Route queries by kind to per-kind BatchRunners and drain them.

    One engine per kind is built lazily at the first query of that
    kind (column count ``batch``); ``run()`` drains every kind's
    queue through continuous-batching refill and returns the
    responses in retirement order.  ``deadline_s`` is the batch
    collector's wait-for-more budget (0 = serve whatever is queued —
    the offline/smoke mode).

    ``slo_ms`` maps query kinds to per-kind latency targets in
    milliseconds (SLO good/violation counters + the rolling burn-rate
    gauge); ``metrics`` is a lux_tpu.metrics.Registry to share, None
    for a fresh private one, or False to disable metrics entirely
    (the overhead-A/B switch, PERF_NOTES round 17)."""

    def __init__(self, g, batch: int = 4, *, num_parts: int = 1,
                 mesh=None, exchange: str = "auto",
                 health: bool = False, weighted: bool = False,
                 seg_iters: int = DEFAULT_SEG_ITERS,
                 tol: float = 1e-8, deadline_s: float = 0.0,
                 slo_ms: dict | None = None, metrics=None,
                 snapshot_every_s: float = 1.0, on_boundary=None,
                 replica: str | None = None, live=None,
                 cache: bool | AnswerCache = False, mem=None):
        self.g = g
        # live-graph serving (round 20, lux_tpu/livegraph.py):
        # ``live`` mutates under the queries — submit pins each
        # query's admission epoch from the live view, the push
        # runners apply the delta-relax step at boundaries, and
        # ``mutate``/``refresh_live`` are the ingest/compaction
        # surfaces.  ``g`` must be the live graph's CURRENT base
        # (engines and oracles key off it).
        self.live = live
        if live is not None and g is not live.base:
            raise ValueError(
                "Server(live=...) requires g to be live.base — the "
                "engines must serve the live graph's own base "
                "generation")
        if cache is True:
            self.cache: AnswerCache | None = \
                AnswerCache.from_slo(slo_ms)
        elif cache:
            self.cache = cache
        else:
            self.cache = None
        # fleet hooks (lux_tpu/fleet.py): the subprocess replica
        # worker runs a whole Server and needs its runners to beat
        # the replica board (and fire kill plans) at every boundary
        self.on_boundary = on_boundary
        self.replica = replica
        # round-22 memory observatory: a memwatch.MemoryTrail the
        # runners sample at every segment boundary (assignable after
        # construction too — runners are built lazily on first use)
        self.mem = mem
        self.batch = int(batch)
        self.opts = dict(num_parts=num_parts, mesh=mesh,
                         exchange=exchange, health=health)
        self.weighted = bool(weighted)
        self.seg_iters = int(seg_iters)
        self.tol = float(tol)
        self.deadline_s = float(deadline_s)
        self.slo_ms = dict(slo_ms or {})
        for k in self.slo_ms:
            if k not in KINDS:
                raise ValueError(f"slo_ms names unknown kind {k!r}; "
                                 f"choose from {KINDS}")
        if metrics is False:
            self.metrics = None
        elif metrics is None:
            from lux_tpu import metrics as metrics_mod
            self.metrics = metrics_mod.Registry()
        else:
            self.metrics = metrics
        if self.cache is not None:
            self.cache.set_metrics(self.metrics, replica)
        self.snapshot_every_s = float(snapshot_every_s)
        self._last_snapshot = 0.0
        self._collectors: dict[str, BatchCollector] = {}
        self._runners: dict[str, _RunnerBase] = {}
        self._next_qid = 0

    def _collector(self, kind: str) -> BatchCollector:
        if kind not in KINDS:
            raise ValueError(f"unknown query kind {kind!r}; choose "
                             f"from {KINDS}")
        return self._collectors.setdefault(
            kind, BatchCollector(metrics=self.metrics, kind=kind))

    def _runner(self, kind: str) -> _RunnerBase:
        if kind not in self._runners:
            mkw = dict(metrics=self.metrics,
                       slo_ms=self.slo_ms.get(kind),
                       live=self.live, cache=self.cache)
            if kind == "pagerank":
                self._runners[kind] = PullBatchRunner(
                    kind, self.g, self.batch,
                    seg_iters=self.seg_iters, tol=self.tol,
                    **mkw, **self.opts)
            else:
                self._runners[kind] = PushBatchRunner(
                    kind, self.g, self.batch,
                    weighted=self.weighted,
                    seg_iters=self.seg_iters, **mkw, **self.opts)
            self._runners[kind].on_boundary = self.on_boundary
            self._runners[kind].replica = self.replica
            self._runners[kind].mem = self.mem
        return self._runners[kind]

    def set_metrics(self, registry) -> None:
        """Re-point every collector and runner at ``registry`` (or
        None to disable).  The load harness uses this to give each
        ramp step a FRESH registry without rebuilding the engines —
        series are fetched from the registry at use time, so the swap
        is complete at the next boundary."""
        self.metrics = registry
        for coll in self._collectors.values():
            coll.metrics = registry
        for runner in self._runners.values():
            runner.metrics = registry
        if self.cache is not None:
            self.cache.set_metrics(registry, self.replica)

    def emit_metrics_snapshot(self, **extra):
        """Publish a ``metrics_snapshot`` telemetry event for this
        server's registry (None when metrics are disabled or no
        event sink is active)."""
        if self.metrics is None:
            return None
        return self.metrics.emit_snapshot(**extra)

    def _admission_epoch(self, kind: str) -> int | None:
        return admission_epoch(self.live, kind)

    def submit(self, kind: str, source: int | None = None,
               reset=None, tenant: str = "default",
               priority: int = 0,
               deadline_s: float | None = None) -> int:
        qid = self._next_qid
        self._next_qid += 1
        req = Request(qid=qid, kind=kind,
                      source=None if source is None else int(source),
                      reset=(None if reset is None
                             else np.asarray(reset, np.float32)),
                      t_enqueue=time.monotonic(), tenant=str(tenant),
                      priority=int(priority),
                      deadline_s=(None if deadline_s is None
                                  else float(deadline_s)),
                      # stamp + admission-ledger entry in ONE lock
                      # acquisition: the generation must survive
                      # until this query retires, and resident pins
                      # alone cannot protect it while QUEUED;
                      # released per response in run()
                      epoch=admit_query(self.live, kind))
        if self.metrics is not None:
            self.metrics.counter("serve_queries_total",
                                 kind=kind).inc()
        self._collector(kind).put(req)
        _emit("query_enqueue", qid=qid, query_kind=kind,
              source=req.source, queued=len(self._collector(kind)))
        return qid

    def mutate(self, src, dst, weights=None,
               op: str = "append") -> int:
        """Ingest path: publish one mutation batch into the live
        graph (WAL-journaled, one new epoch).  ``op`` routes the
        full round-21 algebra: "append" (default), "delete"
        (weights ignored), "reweight" (weights are the NEW values).
        Raises livegraph.DeltaFullError when ingest has outrun
        compaction — the backpressure signal the fleet's admission
        converts into a typed ``AdmissionError(reason="delta_full")``
        shed (lux_tpu/fleet.py)."""
        if self.live is None:
            raise ValueError("mutate() needs a live graph "
                             "(Server(live=LiveGraph(...)))")
        if op == "append":
            return self.live.append_edges(src, dst, weights)
        if op == "delete":
            return self.live.delete_edges(src, dst)
        if op == "reweight":
            return self.live.reweight_edges(src, dst, weights)
        raise ValueError(f"unknown mutation op {op!r}; choose from "
                         f"('append', 'delete', 'reweight')")

    def slo_burn(self) -> float:
        """Worst per-kind rolling SLO-burn fraction across this
        server's runners (0.0 before any SLO accounting) — the
        CompactionScheduler's backoff input
        (livegraph.CompactionScheduler(burn=server.slo_burn))."""
        worst = 0.0
        for r in self._runners.values():
            if r._slo_window:
                worst = max(worst, sum(r._slo_window)
                            / len(r._slo_window))
        return worst

    def refresh_live(self) -> None:
        """Adopt the live graph's NEW generation after a compaction:
        drop the runners so the next drain rebuilds engines over the
        compacted base.  Refuses while anything is resident, or
        while a QUEUED query pins an epoch the new base cannot
        REPRODUCE: push kinds replay any epoch >= base_epoch via the
        per-column delta mask (the post-compact delta holds exactly
        the mutations past base_epoch, so later ingest does NOT
        strand an already-queued query), pull kinds only the base
        generation itself — anything older was folded away and
        adoption would serve a torn view."""
        if self.live is None:
            return
        # list(): a submitter thread may add a new kind's collector
        # mid-iteration (same race run() guards against)
        for kind, coll in list(self._collectors.items()):
            stale = [req for req in coll.pending_requests()
                     if not _epoch_reproducible(self.live, req)]
            if stale:
                raise RuntimeError(
                    f"refresh_live with {len(stale)} {kind!r} "
                    f"query(ies) queued at an epoch the new "
                    f"generation cannot reproduce — drain first")
        for kind, r in self._runners.items():
            if r._occupied():
                raise RuntimeError(
                    f"refresh_live with resident {kind!r} columns — "
                    f"drain first")
        self.g = self.live.base
        self._runners.clear()

    def run(self) -> list[Response]:
        """Drain every kind's queue; returns responses in retirement
        order (continuous batching: later queries refill columns
        freed by earlier retirements).  Publishes a periodic
        ``metrics_snapshot`` event (at most one per
        ``snapshot_every_s`` of non-empty drains — the cadence a
        long-lived serving loop rides; ``emit_metrics_snapshot()``
        snapshots on demand)."""
        if self.live is not None and self.g is not self.live.base:
            # generation adoption is ENFORCED, not caller etiquette:
            # serving on a stale base after a compaction converges
            # old-base + empty delta — a wrong answer whose
            # answer_epoch still equals its admission epoch, so the
            # torn-epoch audit can never see it.  A wrong answer is
            # a crash, never a published number.
            raise RuntimeError(
                "live graph compacted to a new generation — call "
                "refresh_live() before serving")
        if self.cache is not None and self.live is not None:
            # invalidation on epoch advance: entries keyed to epochs
            # no view still exposes can never hit again — drop them
            self.cache.sweep({k: self._admission_epoch(k)
                              for k in KINDS})
        out: list[Response] = []
        # list(): submit() may add a NEW kind's collector from a
        # submitter thread while an open-loop drain iterates
        for kind, coll in list(self._collectors.items()):
            while len(coll):
                out += self._runner(kind).drain(coll, self.deadline_s)
        if self.live is not None:
            # one release per retired response: the admit() taken at
            # submit ends exactly when the answer leaves the server
            for _ in out:
                self.live.release()
        now = time.monotonic()
        if out and now - self._last_snapshot >= self.snapshot_every_s:
            self._last_snapshot = now
            self.emit_metrics_snapshot()
        return out


# ---------------------------------------------------------------------
# smoke: python -m lux_tpu.serve

def _smoke_graph(scale: int, ef: int, seed: int = 0):
    from lux_tpu.graph import Graph
    r = np.random.default_rng(seed)
    nv = 1 << scale
    ne = nv * ef
    return Graph.from_edges(r.integers(0, nv, ne),
                            r.integers(0, nv, ne), nv)


def _check_answers(g, responses) -> int:
    """Verify every response against the apps' batched NumPy oracles;
    returns the mismatch count."""
    from lux_tpu.apps import components, pagerank, sssp
    bad = 0
    for r in responses:
        if r.kind == "sssp":
            ref = sssp.reference_sssp_batched(g, [r.source])[:, 0]
            ref = np.where(ref >= int(sssp.HOP_INF),
                           int(sssp.HOP_INF), ref)
            ok = np.array_equal(r.answer.astype(np.int64), ref)
        elif r.kind == "components":
            ref = components.reference_components_batched(
                g, [r.source])[:, 0]
            ok = np.array_equal(r.answer.astype(np.int64), ref)
        else:
            reset = pagerank.one_hot_resets(g.nv, [r.source])
            ref = pagerank.reference_pagerank_batched(
                g, reset, max(1, r.iters))[:, 0]
            ok = bool(np.allclose(r.answer, ref, atol=5e-5))
        if not ok:
            bad += 1
            print(f"MISMATCH qid={r.qid} kind={r.kind} "
                  f"source={r.source}")
    return bad


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m lux_tpu.serve",
        description="continuous-batching serve smoke: 2B mixed "
                    "queries drain through refill; answers are "
                    "oracle-checked")
    ap.add_argument("-scale", type=int, default=9,
                    help="graph scale (nv = 2**scale; default 9)")
    ap.add_argument("-ef", type=int, default=8)
    ap.add_argument("-batch", type=int, default=4,
                    help="engine column count B (default 4)")
    ap.add_argument("-queries", type=int, default=0,
                    help="total mixed queries (default 2B)")
    ap.add_argument("-kinds", default="sssp,components,pagerank",
                    help="comma list of query kinds to mix")
    ap.add_argument("-np", type=int, default=2, dest="num_parts")
    ap.add_argument("-seg-iters", type=int, default=2,
                    dest="seg_iters",
                    help="iterations per serve segment (the refill "
                         "cadence)")
    ap.add_argument("-seed", type=int, default=0)
    ap.add_argument("-events", default=None, metavar="FILE",
                    help="append the per-query telemetry trail as "
                         "JSONL (render: scripts/events_summary.py)")
    ap.add_argument("-no-check", action="store_true", dest="no_check",
                    help="skip the oracle verification")
    args = ap.parse_args(argv)

    from lux_tpu import telemetry

    kinds = [k.strip() for k in args.kinds.split(",") if k.strip()]
    for k in kinds:
        if k not in KINDS:
            print(f"error: unknown kind {k!r}")
            return 2
    g = _smoke_graph(args.scale, args.ef, args.seed)
    n_queries = args.queries or 2 * args.batch
    rng = np.random.default_rng(args.seed + 1)

    ev = telemetry.EventLog(args.events) if args.events else \
        telemetry.EventLog()
    with telemetry.use(events=ev):
        ev.emit("run_start", schema=telemetry.SCHEMA, app="serve",
                file=f"<rmat{args.scale}>", mesh=1,
                np=args.num_parts)
        srv = Server(g, batch=args.batch, num_parts=args.num_parts,
                     seg_iters=args.seg_iters)
        # mixed-kind queue of 2B queries, biased so the primary kind
        # OVERSUBSCRIBES its B columns — later queries must wait for
        # retirements and enter through continuous-batching refill
        others = kinds[1:]
        seq = [others[i - 1] if 0 < i <= len(others) else kinds[0]
               for i in range(n_queries)]
        for k in seq:
            srv.submit(k, source=int(rng.integers(0, g.nv)))
        t0 = time.perf_counter()
        responses = srv.run()
        elapsed = time.perf_counter() - t0
        ev.emit("run_done", seconds=round(elapsed, 6),
                iters=sum(r.iters for r in responses))
    refills = sum(1 for e in ev.events
                  if e["kind"] == "serve_refill"
                  and e.get("retired", 0) and e.get("filled", 0))
    ev.close()

    lat = sorted(r.latency_s for r in responses)
    p50 = lat[len(lat) // 2] if lat else 0.0
    for r in responses:
        print(f"query {r.qid} [{r.kind}] source={r.source}: "
              f"{r.iters} iters over {r.segments} segment(s), "
              f"latency {r.latency_s * 1e3:.1f} ms"
              + ("" if r.converged else " (SEGMENT CAP)"))
    print(f"# served {len(responses)}/{n_queries} queries "
          f"(B={args.batch}, {len(kinds)} kind(s)) in {elapsed:.2f}s; "
          f"p50 latency {p50 * 1e3:.1f} ms, max "
          f"{(lat[-1] if lat else 0) * 1e3:.1f} ms; "
          f"{refills} retire+refill boundary(ies)")
    if len(responses) != n_queries:
        print("error: queue did not drain")
        return 1
    if n_queries > args.batch and not refills:
        print("error: oversubscribed queue drained without any "
              "continuous-batching refill")
        return 1
    if not args.no_check:
        bad = _check_answers(g, responses)
        if bad:
            print(f"error: {bad} answer(s) mismatched their oracle")
            return 1
        print("# all answers match their NumPy oracles")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
