"""Multi-query serving front-end: request batching + continuous refill.

ROADMAP item 2's front-end: production graph services answer BATCHES
of queries (k-source shortest paths, personalized PageRank with
per-user reset vectors, seeded reachability), and the engines now
carry a query-batch axis ``[vpad, B]`` so ONE state-table gather
serves every query per iteration (engine/program.py ``batch``;
delivered cost ~9/B ns/edge/query, PERF_NOTES "query batching").
This module is the continuous-batching layer on top — the LLM-serving
idiom applied to graph queries, on the segmented/telemetry substrate
PRs 1-8 built:

- a **request queue** (``Server.submit`` / ``BatchCollector``): each
  request is one query (a source vertex, or a reset distribution for
  personalized PageRank); the collector takes up to B queries, or
  whatever has arrived when the collection deadline expires.
- a **BatchRunner** per query kind holding ONE batched engine with a
  fixed column count B.  Queries occupy columns; free columns are
  IDLE (push: all-inactive, contributing the reduce identity through
  the ordinary pre-gather mask; pull: a converged fixed point whose
  updates are no-ops) — the retired-column identity rule
  (ARCHITECTURE.md "Query batching & serving").
- segments run on the EXISTING drivers: push kinds converge through
  ``segmented.converge_segments`` and pull kinds through
  ``segmented.run_segments``, with the continuous-batching refill
  implemented as the drivers' documented ``on_segment`` hook — so
  duration budgeting, telemetry segment events, iter-stats counters
  and the health watchdog all compose unchanged.
- at each segment boundary the hook RETIRES converged columns (push:
  the column's frontier is empty; pull: the column's residual fell
  under ``tol``), scatters their answers into per-query
  :class:`Response` objects, and REFILLS the freed columns from the
  queue (pull refills also swap the column's reset vector in place
  via ``PullEngine.update_program_arrays`` — no recompile).
- per-query telemetry: ``query_enqueue`` / ``query_start`` /
  ``query_done`` events (latency, wait, iterations, segments) plus a
  ``serve_refill`` event per boundary — rendered and validated by
  scripts/events_summary.py.
- streaming SLO metrics (round 17, lux_tpu/metrics.py): every Server
  owns a metrics Registry (``metrics=`` to share or ``metrics=False``
  to disable — the overhead-A/B switch) fed HOST-side at segment
  boundaries only (the hot-path-metrics lint contract): queue depth
  and collect wait-time on ``BatchCollector.collect``, batch
  occupancy / refill and segment counters per ``BatchRunner``
  boundary, per-kind latency histograms at retire, and — with
  ``Server(slo_ms={kind: target_ms})`` — per-kind SLO accounting:
  ``serve_slo_good_total`` / ``serve_slo_violation_total`` counters
  plus a rolling burn-rate gauge (violating fraction over the last
  ``SLO_WINDOW`` retirements; ARCHITECTURE.md "Serving metrics &
  SLOs" has the series catalogue).  ``run()`` publishes a
  ``metrics_snapshot`` telemetry event per drain; scripts/loadgen.py
  reads the snapshots back and scripts/events_summary.py cross-audits
  them against the raw ``query_done`` stream.

Costs and debts: the refill path fetches the [nv, B] state at
boundaries that retire or fill columns (host scatter + re-place) —
O(state) per boundary, fine for the CPU mesh and small B; the
device-side column scatter and the on-device batch sweep are carried
debts (lux_tpu/observe.py DEBTS "batch-sweep-on-device").

Smoke: ``python -m lux_tpu.serve`` builds a small random graph,
enqueues 2B mixed queries (sssp + components + pagerank), drains them
through continuous-batching refill, and verifies every per-query
answer against the apps' batched NumPy oracles (exit 1 on any
mismatch).
"""

from __future__ import annotations

import dataclasses
import queue as _queuemod
import threading
import time
from typing import Callable

import numpy as np

DEFAULT_SEG_ITERS = 4
KINDS = ("sssp", "components", "pagerank")

# rolling SLO burn-rate window: the violating fraction over the last
# SLO_WINDOW retirements per kind (a short multi-batch horizon — long
# enough to smooth one batch's retirements, short enough that a burn
# shows within a few boundaries)
SLO_WINDOW = 64


@dataclasses.dataclass
class Request:
    """One query: ``source`` for sssp/components (and one-hot
    pagerank); ``reset`` [nv] overrides it for personalized
    pagerank.  ``tenant``/``priority``/``deadline_s`` are the
    serving-tier admission fields (lux_tpu/fleet.py): plain Servers
    ignore them; the fleet dispatcher quotes quotas per tenant,
    collects deadline-priority (PriorityCollector) and sheds against
    the deadline."""
    qid: int
    kind: str
    source: int | None = None
    reset: np.ndarray | None = None
    t_enqueue: float = 0.0
    tenant: str = "default"
    priority: int = 0
    deadline_s: float | None = None


@dataclasses.dataclass
class Response:
    qid: int
    kind: str
    source: int | None
    answer: np.ndarray          # [nv] labels / distances / ranks
    iters: int                  # engine iterations while resident
    segments: int               # boundaries the query lived through
    latency_s: float            # enqueue -> retire
    wait_s: float               # enqueue -> column assignment
    converged: bool = True      # False: retired on the segment cap


class _Drained(Exception):
    """Raised by the pull hook when the queue is empty and every
    column is idle — the documented ``on_segment`` abort path of
    ``segmented.run_segments``."""


class BatchCollector:
    """Thread-safe request queue + the collect-up-to-B-or-deadline
    batching rule.  ``put`` is called by ``Server.submit`` (any
    thread); ``collect(n, deadline_s)`` returns up to ``n`` requests,
    waiting at most ``deadline_s`` for the FIRST one and then taking
    only what has already arrived (a deadline of 0 never blocks).

    With ``metrics``/``kind`` set (Server wires them), ``put`` and
    ``collect`` keep the ``serve_queue_depth`` gauge current and
    ``collect`` observes each request's queue wait (enqueue ->
    collection) into ``serve_wait_seconds`` — host-side, boundary-
    cadence calls only.  ``replica`` (the fleet, lux_tpu/fleet.py)
    labels the depth GAUGE per replica — N replicas sharing one
    (name, kind) gauge would be last-writer-wins; shared counters
    and histograms merge correctly and stay fleet-wide."""

    def __init__(self, metrics=None, kind: str | None = None,
                 replica: str | None = None):
        self._q: _queuemod.Queue = _queuemod.Queue()
        self.metrics = metrics
        self.kind = kind
        self.replica = replica

    def _labels(self) -> dict:
        if self.replica is None:
            return {"kind": self.kind}
        return {"kind": self.kind, "replica": self.replica}

    def _depth(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge("serve_queue_depth",
                               **self._labels()).set(self._q.qsize())

    def put(self, req: Request) -> None:
        self._q.put(req)
        self._depth()

    def __len__(self) -> int:
        return self._q.qsize()

    def collect(self, n: int, deadline_s: float = 0.0) -> list[Request]:
        out: list[Request] = []
        deadline = time.monotonic() + max(0.0, deadline_s)
        while len(out) < n:
            timeout = deadline - time.monotonic()
            try:
                if not out and timeout > 0:
                    out.append(self._q.get(timeout=timeout))
                else:
                    out.append(self._q.get_nowait())
            except _queuemod.Empty:
                break
        if self.metrics is not None:
            self._depth()
            now = time.monotonic()
            wait = self.metrics.histogram("serve_wait_seconds",
                                          kind=self.kind)
            for req in out:
                wait.observe(max(0.0, now - req.t_enqueue))
        return out


class PriorityCollector(BatchCollector):
    """Deadline-priority request queue (the fleet dispatcher's
    admission queue, lux_tpu/fleet.py) replacing the base collector's
    pure FIFO with a PINNED ordering rule:

    - requests collect highest ``priority`` first, FIFO within a
      priority — EXCEPT
    - a request already past HALF its ``deadline_s`` is AGED: aged
      requests outrank every un-aged one (among themselves: earliest
      absolute deadline first, then FIFO).

    Without the aging clause a saturated high-priority stream
    displaces low-priority requests indefinitely; with it a displaced
    request's extra wait is bounded by half its own deadline plus one
    collection round (tests/test_serve.py pins both halves with a
    deterministic injected clock).  ``collect``'s deadline semantics
    match the base class: wait at most ``deadline_s`` for the FIRST
    request, then take only what has already arrived."""

    def __init__(self, metrics=None, kind: str | None = None,
                 replica: str | None = None,
                 now: Callable[[], float] = time.monotonic):
        # deliberately NOT calling super().__init__: the base Queue
        # is replaced wholesale by the condition-guarded list
        # (collection is a SORT, not a pop), and allocating it would
        # leave a dead always-empty queue for any base path to
        # silently read
        self.metrics = metrics
        self.kind = kind
        self.replica = replica
        self.now = now
        self._items: list[Request] = []
        self._cv = threading.Condition()

    def _depth(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge("serve_queue_depth",
                               **self._labels()).set(len(self))

    def put(self, req: Request) -> None:
        with self._cv:
            self._items.append(req)
            self._cv.notify()
        self._depth()

    def __len__(self) -> int:
        with self._cv:
            return len(self._items)

    def _key(self, idx: int, req: Request, now: float):
        aged = (req.deadline_s is not None
                and now - req.t_enqueue >= 0.5 * req.deadline_s)
        if aged:
            # aged bucket outranks everything; earliest absolute
            # deadline first so the most endangered request leads
            return (0, req.t_enqueue + req.deadline_s, idx)
        return (1, -int(req.priority), idx)

    def collect(self, n: int, deadline_s: float = 0.0) -> list[Request]:
        deadline = time.monotonic() + max(0.0, deadline_s)
        with self._cv:
            while not self._items:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                self._cv.wait(timeout)
            now = self.now()
            order = sorted(range(len(self._items)),
                           key=lambda i: self._key(i, self._items[i],
                                                   now))
            take = sorted(order[:max(0, n)])
            out = [self._items[i] for i in order[:max(0, n)]]
            for i in reversed(take):
                del self._items[i]
        if self.metrics is not None:
            self._depth()
            t = time.monotonic()
            wait = self.metrics.histogram("serve_wait_seconds",
                                          kind=self.kind)
            for req in out:
                wait.observe(max(0.0, t - req.t_enqueue))
        return out


@dataclasses.dataclass
class _Slot:
    req: Request
    t_start: float
    iter_start: int
    segments: int = 0


def _emit(event: str, **fields):
    from lux_tpu import telemetry
    telemetry.current().emit(event, **fields)


class _RunnerBase:
    """Shared slot bookkeeping for one batched engine of width B."""

    def __init__(self, kind: str, B: int, seg_iters: int,
                 max_segments: int, metrics=None,
                 slo_ms: float | None = None):
        self.kind = kind
        self.B = int(B)
        self.seg_iters = int(seg_iters)
        self.max_segments = int(max_segments)
        self.slots: list[_Slot | None] = [None] * self.B
        self.responses: list[Response] = []
        self.metrics = metrics
        self.slo_ms = None if slo_ms is None else float(slo_ms)
        # serving-tier hooks (lux_tpu/fleet.py): ``replica`` labels
        # the per-query events with the runner's replica name, and
        # ``on_boundary(runner)`` fires at the TOP of every segment
        # boundary — the fleet's heartbeat-beat + chaos-kill-plan
        # injection point (an exception raised there propagates out
        # of drain() as a mid-drain replica death)
        self.replica: str | None = None
        self.on_boundary: Callable | None = None
        # rolling SLO window: True per retirement = violation
        import collections
        self._slo_window = collections.deque(maxlen=SLO_WINDOW)

    def _rep(self) -> dict:
        return {} if self.replica is None else {"replica": self.replica}

    def _free_cols(self):
        return [c for c, s in enumerate(self.slots) if s is None]

    def _occupied(self):
        return [c for c, s in enumerate(self.slots) if s is not None]

    def _start(self, col: int, req: Request, total_iters: int):
        now = time.monotonic()
        self.slots[col] = _Slot(req=req, t_start=now,
                                iter_start=total_iters)
        _emit("query_start", qid=req.qid, query_kind=self.kind,
              col=col,
              wait_s=round(now - req.t_enqueue, 6), **self._rep())

    def _retire(self, col: int, answer: np.ndarray, total_iters: int,
                converged: bool = True):
        slot = self.slots[col]
        self.slots[col] = None
        now = time.monotonic()
        resp = Response(
            qid=slot.req.qid, kind=self.kind, source=slot.req.source,
            answer=answer, iters=total_iters - slot.iter_start,
            segments=slot.segments,
            latency_s=now - slot.req.t_enqueue,
            wait_s=slot.t_start - slot.req.t_enqueue,
            converged=converged)
        self.responses.append(resp)
        slo = {}
        if self.slo_ms is not None:
            slo_ok = resp.latency_s * 1e3 <= self.slo_ms
            slo = {"slo_ms": self.slo_ms, "slo_ok": slo_ok}
            self._slo_window.append(not slo_ok)
        if self.metrics is not None:
            m = self.metrics
            m.histogram("serve_latency_seconds",
                        kind=self.kind).observe(resp.latency_s)
            m.counter("serve_retired_total", kind=self.kind).inc()
            if not converged:
                m.counter("serve_segment_cap_total",
                          kind=self.kind).inc()
            if self.slo_ms is not None:
                m.counter("serve_slo_good_total" if slo["slo_ok"]
                          else "serve_slo_violation_total",
                          kind=self.kind).inc()
                burn = (sum(self._slo_window)
                        / max(1, len(self._slo_window)))
                m.gauge("serve_slo_burn_rate",
                        kind=self.kind).set(burn)
        _emit("query_done", qid=resp.qid, query_kind=self.kind,
              col=col,
              iters=resp.iters, segments=resp.segments,
              latency_s=round(resp.latency_s, 6),
              wait_s=round(resp.wait_s, 6), converged=converged,
              **slo, **self._rep())
        return resp

    def _boundary_metrics(self, retired: int, filled: int,
                          queued: int) -> None:
        """Per-segment-boundary series (host-side by construction —
        the drivers' on_segment hooks are the only callers): batch
        occupancy, segment count, retire/refill rates."""
        if self.metrics is None:
            return
        m = self.metrics
        # counters are SHARED fleet-wide (they sum correctly across
        # replicas); the gauges are per-replica quantities and carry
        # the replica label when one is set — N replicas writing one
        # (name, kind) gauge would be last-writer-wins noise
        m.counter("serve_segments_total", kind=self.kind).inc()
        m.gauge("serve_batch_occupancy", kind=self.kind,
                **self._rep()).set(len(self._occupied()))
        m.gauge("serve_queue_depth", kind=self.kind,
                **self._rep()).set(queued)
        if filled:
            m.counter("serve_refilled_total",
                      kind=self.kind).inc(filled)


class PushBatchRunner(_RunnerBase):
    """Continuous-batching runner for push kinds (sssp /
    components): one batched PushEngine, columns retire when their
    per-query frontier empties, refill rides
    ``converge_segments``'s ``on_segment`` hook."""

    def __init__(self, kind: str, g, B: int, *, num_parts: int = 1,
                 mesh=None, exchange: str = "auto",
                 health: bool = False, weighted: bool = False,
                 seg_iters: int = DEFAULT_SEG_ITERS,
                 max_segments: int = 10_000, metrics=None,
                 slo_ms: float | None = None):
        super().__init__(kind, B, seg_iters, max_segments,
                         metrics=metrics, slo_ms=slo_ms)
        self.g = g
        self.weighted = bool(weighted and kind == "sssp")
        placeholder = [0] * self.B
        if kind == "sssp":
            from lux_tpu.apps import sssp as app
            self.eng = app.build_engine(
                g, sources=placeholder, num_parts=num_parts,
                mesh=mesh, weighted=self.weighted,
                exchange=exchange, health=health)
            self._inf = (app.DIST_INF if self.weighted
                         else app.HOP_INF)
            self._dtype = np.float32 if self.weighted else np.int32
        elif kind == "components":
            from lux_tpu.apps import components as app
            self.eng = app.build_engine(
                g, sources=placeholder, num_parts=num_parts,
                mesh=mesh, exchange=exchange, health=health)
            self._inf = np.int32(-1)
            self._dtype = np.int32
        else:
            raise ValueError(f"unknown push kind {kind!r}")

    def _col_init(self, req: Request):
        """(label [nv], active [nv]) for a fresh query column."""
        nv = self.g.nv
        s = int(req.source)
        if not 0 <= s < nv:
            raise ValueError(f"query {req.qid}: source {s} out of "
                             f"range [0, {nv})")
        lab = np.full(nv, self._inf, dtype=self._dtype)
        act = np.zeros(nv, dtype=bool)
        lab[s] = s if self.kind == "components" else 0
        act[s] = True
        return lab, act

    def drain(self, collector: BatchCollector,
              deadline_s: float = 0.0) -> list[Response]:
        """Serve until the collector is empty and every column is
        idle; returns the responses retired during this drain."""
        import jax
        import jax.numpy as jnp

        from lux_tpu.segmented import converge_segments

        eng, sg = self.eng, self.eng.sg
        nv, B = self.g.nv, self.B
        n0 = len(self.responses)

        lab_h = np.full((nv, B), self._inf, dtype=self._dtype)
        act_h = np.zeros((nv, B), dtype=bool)
        filled = self._fill(lab_h, act_h, collector, 0, deadline_s)
        if not filled:
            return []
        label, active = eng.place(sg.to_padded(lab_h),
                                  sg.to_padded(act_h))

        def hook(label, active, total, cnt):
            if self.on_boundary is not None:
                self.on_boundary(self)
            for s in self.slots:
                if s is not None:
                    s.segments += 1
            counts = np.asarray(jax.device_get(
                jnp.sum(active, axis=tuple(range(active.ndim - 1)))))
            done = [c for c in self._occupied()
                    if counts[c] == 0
                    or self.slots[c].segments >= self.max_segments]
            want_fill = len(collector) > 0 and (
                done or self._free_cols())
            if not done and not want_fill:
                self._boundary_metrics(0, 0, len(collector))
                return None
            lab_h = sg.from_padded(np.asarray(jax.device_get(label)))
            act_h = sg.from_padded(np.asarray(jax.device_get(active)))
            for c in done:
                self._retire(c, lab_h[:, c].copy(), total,
                             converged=bool(counts[c] == 0))
                lab_h[:, c] = self._inf
                act_h[:, c] = False
            n_filled = self._fill(lab_h, act_h, collector, total,
                                  deadline_s)
            _emit("serve_refill", query_kind=self.kind,
                  retired=len(done),
                  filled=n_filled, occupied=len(self._occupied()),
                  queued=len(collector))
            self._boundary_metrics(len(done), n_filled,
                                   len(collector))
            return eng.place(sg.to_padded(lab_h), sg.to_padded(act_h))

        converge_segments(eng, label, active, self.seg_iters,
                          on_segment=hook)
        return self.responses[n0:]

    def _fill(self, lab_h, act_h, collector, total_iters,
              deadline_s) -> int:
        free = self._free_cols()
        reqs = collector.collect(len(free), deadline_s)
        for col, req in zip(free, reqs):
            lab_h[:, col], act_h[:, col] = self._col_init(req)
            self._start(col, req, total_iters)
        return len(reqs)


class PullBatchRunner(_RunnerBase):
    """Continuous-batching runner for personalized PageRank: one
    batched PullEngine; a column retires when its per-query residual
    (max-abs state change over a segment's last iteration, computed
    at the boundary) falls under ``tol``; refill swaps the column's
    reset vector in place (``PullEngine.update_program_arrays``)."""

    def __init__(self, kind: str, g, B: int, *, num_parts: int = 1,
                 mesh=None, exchange: str = "auto",
                 health: bool = False,
                 seg_iters: int = DEFAULT_SEG_ITERS,
                 tol: float = 1e-8, max_segments: int = 500,
                 metrics=None, slo_ms: float | None = None):
        super().__init__(kind, B, seg_iters, max_segments,
                         metrics=metrics, slo_ms=slo_ms)
        if kind != "pagerank":
            raise ValueError(f"unknown pull kind {kind!r}")
        from lux_tpu.apps import pagerank as app
        self.g = g
        self.app = app
        self.tol = float(tol)
        # idle columns carry the uniform reset's fixed-point-bound
        # trajectory — cheap, and refilled before they matter
        self.resets = np.full((g.nv, B), 1.0 / g.nv, dtype=np.float32)
        self.eng = app.build_engine(
            g, num_parts=num_parts, mesh=mesh, resets=self.resets,
            exchange=exchange, health=health)

    def _col_reset(self, req: Request) -> np.ndarray:
        if req.reset is not None:
            r = np.asarray(req.reset, np.float32)
            if r.shape != (self.g.nv,):
                raise ValueError(
                    f"query {req.qid}: reset must be [nv], got "
                    f"{r.shape}")
            return r
        return self.app.one_hot_resets(self.g.nv,
                                       [int(req.source)])[:, 0]

    def _col_init(self, reset: np.ndarray) -> np.ndarray:
        deg = np.asarray(self.g.out_degrees, np.float32)
        return np.where(deg > 0, reset / np.maximum(deg, 1),
                        reset).astype(np.float32)

    def drain(self, collector: BatchCollector,
              deadline_s: float = 0.0) -> list[Response]:
        import jax

        from lux_tpu.segmented import run_segments

        eng, sg = self.eng, self.eng.sg
        B = self.B
        n0 = len(self.responses)

        state_h = sg.from_padded(np.asarray(
            self.eng.program.init(sg)))          # [nv, B]
        if not self._fill(state_h, collector, 0, deadline_s):
            return []
        self._push_resets()
        prev = state_h.copy()
        state = eng.place(sg.to_padded(state_h))

        def hook(state, done_iters):
            nonlocal prev
            if self.on_boundary is not None:
                self.on_boundary(self)
            for s in self.slots:
                if s is not None:
                    s.segments += 1
            new = sg.from_padded(np.asarray(jax.device_get(state)))
            # per-query convergence: max-abs state change over the
            # WHOLE segment <= tol (an upper bound on any single
            # iteration's residual — strictly conservative)
            res = np.max(np.abs(new - prev), axis=0)
            done = [c for c in self._occupied()
                    if res[c] <= self.tol
                    or self.slots[c].segments >= self.max_segments]
            for c in done:
                self._retire(c, new[:, c].copy(), done_iters,
                             converged=bool(res[c] <= self.tol))
            n_filled = self._fill(new, collector, done_iters,
                                  deadline_s)
            if done or n_filled:
                _emit("serve_refill", query_kind=self.kind,
                      retired=len(done), filled=n_filled,
                      occupied=len(self._occupied()),
                      queued=len(collector))
            self._boundary_metrics(len(done), n_filled,
                                   len(collector))
            if not self._occupied() and not len(collector):
                raise _Drained()
            prev = new
            if n_filled:
                self._push_resets()
                return eng.place(sg.to_padded(new))
            return None

        try:
            run_segments(eng, state, np.iinfo(np.int32).max,
                         self.seg_iters, on_segment=hook)
        except _Drained:
            pass
        return self.responses[n0:]

    def _push_resets(self):
        self.eng.update_program_arrays(
            reset=self.eng.sg.to_padded(self.resets))

    def _fill(self, state_h, collector, total_iters,
              deadline_s) -> int:
        free = self._free_cols()
        reqs = collector.collect(len(free), deadline_s)
        for col, req in zip(free, reqs):
            reset = self._col_reset(req)
            self.resets[:, col] = reset
            state_h[:, col] = self._col_init(reset)
            self._start(col, req, total_iters)
        return len(reqs)


class Server:
    """Route queries by kind to per-kind BatchRunners and drain them.

    One engine per kind is built lazily at the first query of that
    kind (column count ``batch``); ``run()`` drains every kind's
    queue through continuous-batching refill and returns the
    responses in retirement order.  ``deadline_s`` is the batch
    collector's wait-for-more budget (0 = serve whatever is queued —
    the offline/smoke mode).

    ``slo_ms`` maps query kinds to per-kind latency targets in
    milliseconds (SLO good/violation counters + the rolling burn-rate
    gauge); ``metrics`` is a lux_tpu.metrics.Registry to share, None
    for a fresh private one, or False to disable metrics entirely
    (the overhead-A/B switch, PERF_NOTES round 17)."""

    def __init__(self, g, batch: int = 4, *, num_parts: int = 1,
                 mesh=None, exchange: str = "auto",
                 health: bool = False, weighted: bool = False,
                 seg_iters: int = DEFAULT_SEG_ITERS,
                 tol: float = 1e-8, deadline_s: float = 0.0,
                 slo_ms: dict | None = None, metrics=None,
                 snapshot_every_s: float = 1.0, on_boundary=None,
                 replica: str | None = None):
        self.g = g
        # fleet hooks (lux_tpu/fleet.py): the subprocess replica
        # worker runs a whole Server and needs its runners to beat
        # the replica board (and fire kill plans) at every boundary
        self.on_boundary = on_boundary
        self.replica = replica
        self.batch = int(batch)
        self.opts = dict(num_parts=num_parts, mesh=mesh,
                         exchange=exchange, health=health)
        self.weighted = bool(weighted)
        self.seg_iters = int(seg_iters)
        self.tol = float(tol)
        self.deadline_s = float(deadline_s)
        self.slo_ms = dict(slo_ms or {})
        for k in self.slo_ms:
            if k not in KINDS:
                raise ValueError(f"slo_ms names unknown kind {k!r}; "
                                 f"choose from {KINDS}")
        if metrics is False:
            self.metrics = None
        elif metrics is None:
            from lux_tpu import metrics as metrics_mod
            self.metrics = metrics_mod.Registry()
        else:
            self.metrics = metrics
        self.snapshot_every_s = float(snapshot_every_s)
        self._last_snapshot = 0.0
        self._collectors: dict[str, BatchCollector] = {}
        self._runners: dict[str, _RunnerBase] = {}
        self._next_qid = 0

    def _collector(self, kind: str) -> BatchCollector:
        if kind not in KINDS:
            raise ValueError(f"unknown query kind {kind!r}; choose "
                             f"from {KINDS}")
        return self._collectors.setdefault(
            kind, BatchCollector(metrics=self.metrics, kind=kind))

    def _runner(self, kind: str) -> _RunnerBase:
        if kind not in self._runners:
            mkw = dict(metrics=self.metrics,
                       slo_ms=self.slo_ms.get(kind))
            if kind == "pagerank":
                self._runners[kind] = PullBatchRunner(
                    kind, self.g, self.batch,
                    seg_iters=self.seg_iters, tol=self.tol,
                    **mkw, **self.opts)
            else:
                self._runners[kind] = PushBatchRunner(
                    kind, self.g, self.batch,
                    weighted=self.weighted,
                    seg_iters=self.seg_iters, **mkw, **self.opts)
            self._runners[kind].on_boundary = self.on_boundary
            self._runners[kind].replica = self.replica
        return self._runners[kind]

    def set_metrics(self, registry) -> None:
        """Re-point every collector and runner at ``registry`` (or
        None to disable).  The load harness uses this to give each
        ramp step a FRESH registry without rebuilding the engines —
        series are fetched from the registry at use time, so the swap
        is complete at the next boundary."""
        self.metrics = registry
        for coll in self._collectors.values():
            coll.metrics = registry
        for runner in self._runners.values():
            runner.metrics = registry

    def emit_metrics_snapshot(self, **extra):
        """Publish a ``metrics_snapshot`` telemetry event for this
        server's registry (None when metrics are disabled or no
        event sink is active)."""
        if self.metrics is None:
            return None
        return self.metrics.emit_snapshot(**extra)

    def submit(self, kind: str, source: int | None = None,
               reset=None, tenant: str = "default",
               priority: int = 0,
               deadline_s: float | None = None) -> int:
        qid = self._next_qid
        self._next_qid += 1
        req = Request(qid=qid, kind=kind,
                      source=None if source is None else int(source),
                      reset=(None if reset is None
                             else np.asarray(reset, np.float32)),
                      t_enqueue=time.monotonic(), tenant=str(tenant),
                      priority=int(priority),
                      deadline_s=(None if deadline_s is None
                                  else float(deadline_s)))
        if self.metrics is not None:
            self.metrics.counter("serve_queries_total",
                                 kind=kind).inc()
        self._collector(kind).put(req)
        _emit("query_enqueue", qid=qid, query_kind=kind,
              source=req.source, queued=len(self._collector(kind)))
        return qid

    def run(self) -> list[Response]:
        """Drain every kind's queue; returns responses in retirement
        order (continuous batching: later queries refill columns
        freed by earlier retirements).  Publishes a periodic
        ``metrics_snapshot`` event (at most one per
        ``snapshot_every_s`` of non-empty drains — the cadence a
        long-lived serving loop rides; ``emit_metrics_snapshot()``
        snapshots on demand)."""
        out: list[Response] = []
        # list(): submit() may add a NEW kind's collector from a
        # submitter thread while an open-loop drain iterates
        for kind, coll in list(self._collectors.items()):
            while len(coll):
                out += self._runner(kind).drain(coll, self.deadline_s)
        now = time.monotonic()
        if out and now - self._last_snapshot >= self.snapshot_every_s:
            self._last_snapshot = now
            self.emit_metrics_snapshot()
        return out


# ---------------------------------------------------------------------
# smoke: python -m lux_tpu.serve

def _smoke_graph(scale: int, ef: int, seed: int = 0):
    from lux_tpu.graph import Graph
    r = np.random.default_rng(seed)
    nv = 1 << scale
    ne = nv * ef
    return Graph.from_edges(r.integers(0, nv, ne),
                            r.integers(0, nv, ne), nv)


def _check_answers(g, responses) -> int:
    """Verify every response against the apps' batched NumPy oracles;
    returns the mismatch count."""
    from lux_tpu.apps import components, pagerank, sssp
    bad = 0
    for r in responses:
        if r.kind == "sssp":
            ref = sssp.reference_sssp_batched(g, [r.source])[:, 0]
            ref = np.where(ref >= int(sssp.HOP_INF),
                           int(sssp.HOP_INF), ref)
            ok = np.array_equal(r.answer.astype(np.int64), ref)
        elif r.kind == "components":
            ref = components.reference_components_batched(
                g, [r.source])[:, 0]
            ok = np.array_equal(r.answer.astype(np.int64), ref)
        else:
            reset = pagerank.one_hot_resets(g.nv, [r.source])
            ref = pagerank.reference_pagerank_batched(
                g, reset, max(1, r.iters))[:, 0]
            ok = bool(np.allclose(r.answer, ref, atol=5e-5))
        if not ok:
            bad += 1
            print(f"MISMATCH qid={r.qid} kind={r.kind} "
                  f"source={r.source}")
    return bad


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m lux_tpu.serve",
        description="continuous-batching serve smoke: 2B mixed "
                    "queries drain through refill; answers are "
                    "oracle-checked")
    ap.add_argument("-scale", type=int, default=9,
                    help="graph scale (nv = 2**scale; default 9)")
    ap.add_argument("-ef", type=int, default=8)
    ap.add_argument("-batch", type=int, default=4,
                    help="engine column count B (default 4)")
    ap.add_argument("-queries", type=int, default=0,
                    help="total mixed queries (default 2B)")
    ap.add_argument("-kinds", default="sssp,components,pagerank",
                    help="comma list of query kinds to mix")
    ap.add_argument("-np", type=int, default=2, dest="num_parts")
    ap.add_argument("-seg-iters", type=int, default=2,
                    dest="seg_iters",
                    help="iterations per serve segment (the refill "
                         "cadence)")
    ap.add_argument("-seed", type=int, default=0)
    ap.add_argument("-events", default=None, metavar="FILE",
                    help="append the per-query telemetry trail as "
                         "JSONL (render: scripts/events_summary.py)")
    ap.add_argument("-no-check", action="store_true", dest="no_check",
                    help="skip the oracle verification")
    args = ap.parse_args(argv)

    from lux_tpu import telemetry

    kinds = [k.strip() for k in args.kinds.split(",") if k.strip()]
    for k in kinds:
        if k not in KINDS:
            print(f"error: unknown kind {k!r}")
            return 2
    g = _smoke_graph(args.scale, args.ef, args.seed)
    n_queries = args.queries or 2 * args.batch
    rng = np.random.default_rng(args.seed + 1)

    ev = telemetry.EventLog(args.events) if args.events else \
        telemetry.EventLog()
    with telemetry.use(events=ev):
        ev.emit("run_start", schema=telemetry.SCHEMA, app="serve",
                file=f"<rmat{args.scale}>", mesh=1,
                np=args.num_parts)
        srv = Server(g, batch=args.batch, num_parts=args.num_parts,
                     seg_iters=args.seg_iters)
        # mixed-kind queue of 2B queries, biased so the primary kind
        # OVERSUBSCRIBES its B columns — later queries must wait for
        # retirements and enter through continuous-batching refill
        others = kinds[1:]
        seq = [others[i - 1] if 0 < i <= len(others) else kinds[0]
               for i in range(n_queries)]
        for k in seq:
            srv.submit(k, source=int(rng.integers(0, g.nv)))
        t0 = time.perf_counter()
        responses = srv.run()
        elapsed = time.perf_counter() - t0
        ev.emit("run_done", seconds=round(elapsed, 6),
                iters=sum(r.iters for r in responses))
    refills = sum(1 for e in ev.events
                  if e["kind"] == "serve_refill"
                  and e.get("retired", 0) and e.get("filled", 0))
    ev.close()

    lat = sorted(r.latency_s for r in responses)
    p50 = lat[len(lat) // 2] if lat else 0.0
    for r in responses:
        print(f"query {r.qid} [{r.kind}] source={r.source}: "
              f"{r.iters} iters over {r.segments} segment(s), "
              f"latency {r.latency_s * 1e3:.1f} ms"
              + ("" if r.converged else " (SEGMENT CAP)"))
    print(f"# served {len(responses)}/{n_queries} queries "
          f"(B={args.batch}, {len(kinds)} kind(s)) in {elapsed:.2f}s; "
          f"p50 latency {p50 * 1e3:.1f} ms, max "
          f"{(lat[-1] if lat else 0) * 1e3:.1f} ms; "
          f"{refills} retire+refill boundary(ies)")
    if len(responses) != n_queries:
        print("error: queue did not drain")
        return 1
    if n_queries > args.batch and not refills:
        print("error: oversubscribed queue drained without any "
              "continuous-batching refill")
        return 1
    if not args.no_check:
        bad = _check_answers(g, responses)
        if bad:
            print(f"error: {bad} answer(s) mismatched their oracle")
            return 1
        print("# all answers match their NumPy oracles")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
