"""Live graphs: crash-consistent mutation log, snapshot-isolated
epochs, incremental revalidation, and chaos-drilled compaction.

The reference is a static-graph batch system (its graphs are loaded
once and never mutate, reference pull_model.inl:253-320); the serving
tier built in rounds 14-18 answers live traffic against that frozen
snapshot.  This module makes the graph MUTABLE underneath the queries
with a robustness-first correctness story (ROADMAP item 4):

1. **Durable mutation log** (:class:`MutationLog`): every edge append
   is journaled to a CRC-CHAINED append-only WAL before it is
   visible — record i's CRC32 seeds from record i-1's
   (checkpoint.chained_crc32, the same per-leaf CRC discipline the
   checkpoints carry), so a torn mid-append write (power loss, the
   injected ``faults.WAL_TORN``) breaks the chain at the exact tear
   point.  Replay truncates a torn TAIL and recovers the precise
   pre-append state (bitwise — tests/test_livegraph.py); a broken
   chain FOLLOWED by further whole records cannot be a torn append
   and raises a typed :class:`MutationLogError` instead of replaying
   garbage.  The on-disk header format lives with the other formats
   (format.py ``read_wal_header``: magic/version/nv/capacity — a log
   from a DIFFERENT graph errors instead of replaying foreign
   mutations).

2. **Fixed-capacity delta blocks, snapshot-isolated epochs**:
   published mutations land in fixed-capacity host arrays
   (src/dst/weight/epoch) that are passed to the engines' delta-relax
   step as jit ARGUMENTS — no pair/page plan rebuild, no recompile,
   per append (the Ragged-Paged-Attention idiom from PAPERS.md:
   ragged growth through fixed-shape blocks).  Isolation is BY
   CONSTRUCTION: a published slot is never rewritten (compaction
   swaps in FRESH arrays rather than zeroing), the base generation's
   arrays are never mutated in place, unwritten slots carry an
   i32-max epoch sentinel written LAST — so a reader pinned to epoch
   e sees exactly the edges with ``d_epoch <= e`` no matter how the
   writer thread interleaves, and a torn read is impossible rather
   than merely unlikely.  ``epoch`` is a monotone counter advanced
   once per published append batch; scripts/events_summary.py FAILS
   any trail whose answers were computed at a different epoch than
   their admission pinned (the torn-epoch audit).

3. **Incremental revalidation** (:meth:`LiveGraph.revalidate`):
   frontier-seeded re-convergence — the delta-relax step gathers the
   delta sources from the state table (ONE state-table gather,
   machine-checked against the same audit gather budget as the dense
   iterations: lux_tpu/audit.py matrix configs ``*_live_delta``),
   relaxes the delta edges, epoch-masks per query column, scatters
   min/max into the table, and activates improved destinations; the
   push engine then re-converges only the reachable-from-touched
   region.  NumPy incremental oracles came FIRST per convention
   (apps/sssp.reference_sssp_incremental,
   components.reference_components_incremental) and the device path
   is proved equal to full recompute at the same epoch, bitwise for
   the integer apps.  Measured on CPU it beats full recompute across
   the touched-fraction sweep (scripts/sweep_live.py; PERF_NOTES
   round 20).

4. **Background compaction** (:meth:`LiveGraph.compact`): when delta
   occupancy degrades the delta-drag economics
   (:meth:`compact_economics`, priced with the scalemodel gather
   terms), the delta folds into the base layout
   (``Graph.with_edges`` — a deterministic CSC rebuild) and the
   generation swaps ATOMICALLY under the lock: readers see the old
   (base, delta) pair or the new one, never a mixture.  The WAL
   brackets the fold with COMPACT_START/COMPACT_DONE markers; an
   injected crash between them (``faults.COMPACT_CRASH``) leaves a
   START without a DONE, and recovery comes up on the SURVIVING
   generation (origin base + full replay) — compaction is a LAYOUT
   transition, never a durability transition, so a half-built
   generation can always be discarded.  Serving-tier backpressure:
   when ingest outruns compaction the delta blocks fill and appends
   raise a typed :class:`DeltaFullError`, which the fleet's admission
   sheds as ``AdmissionError(reason="delta_full")``
   (lux_tpu/fleet.py).

Epoch visibility per engine family: the PUSH kinds (sssp /
components) see base + published delta at the latest epoch — their
monotone min/max programs absorb delta edges exactly through the
delta-relax step.  The PULL kinds (pagerank) have no monotone
revalidation (appends change out-degree normalization), so their
snapshot view is the base GENERATION: mutations become visible to
them at compaction, and their queries pin the generation's
``base_epoch``.  Both pinnings are recorded at admission and audited
at answer time (serve.py / scripts/events_summary.py).

Durability scope: the WAL journals MUTATIONS; the base graph is the
caller's (a .lux file or a deterministic generator spec), so recovery
is ``LiveGraph.recover(origin_graph, wal_path)`` — replay the full
log onto the origin and re-fold any completed compactions
(deterministic, hence bitwise).  ``graph_at(epoch)`` materializes the
host Graph as of any epoch — the NumPy-oracle surface every
live-serving answer is checked against (O(total mutations) host
memory; a diagnostic/test surface, documented as such).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import weakref

import numpy as np

from lux_tpu import format as luxfmt
from lux_tpu.checkpoint import chained_crc32
from lux_tpu.graph import Graph

# WAL record kinds (format.py owns the header; the 24-byte record
# layout is [epoch u32, kind u32, a u32, b u32, c u32, crc u32] with
# crc = chained_crc32(first 20 bytes, prev record's crc; the chain
# seeds from the header's CRC so a re-headered log cannot re-validate)
REC_EDGE = 1           # a=src, b=dst, c=float32 weight bits
REC_COMPACT_START = 2  # a=delta count folded, b=new generation
REC_COMPACT_DONE = 3   # a=new generation, b=base epoch after fold

# unwritten delta slots carry this epoch sentinel (written LAST in a
# slot publish) so a concurrent reader's epoch mask can never see a
# half-written slot — the torn-read-free-by-construction invariant
EPOCH_SENTINEL = np.int32(np.iinfo(np.int32).max)


class LiveGraphError(RuntimeError):
    """Base of the live-graph subsystem's typed failures."""


class MutationLogError(LiveGraphError):
    """The mutation log failed verification.  Carries ``path``,
    ``check`` (torn_tail / crc_chain / epoch_order / record_kind /
    compact_pair / capacity_overflow / wal_exists) and ``detail`` —
    the same typed-diagnosis shape as
    format.GraphFormatError, consumed by scripts/fsck_lux.py (exit
    2).  ``torn_tail`` is the RECOVERABLE class: replay truncates it;
    every other check is hard corruption that must never replay."""

    def __init__(self, path: str, check: str, detail: str):
        super().__init__(f"{path}: mutation log [{check}] — {detail}")
        self.path = path
        self.check = check
        self.detail = detail


class DeltaFullError(LiveGraphError):
    """The fixed-capacity delta blocks are full: ingest has outrun
    compaction.  The serving tier's admission converts this into the
    typed ``AdmissionError(reason="delta_full")`` backpressure shed
    (lux_tpu/fleet.py) instead of blocking or silently dropping."""

    def __init__(self, capacity: int):
        super().__init__(
            f"delta blocks full ({capacity} slots): compact before "
            f"appending more mutations")
        self.capacity = capacity


class CompactPinnedError(LiveGraphError):
    """compact() was called while queries still pin the current
    generation — swapping under them would un-mask base edges newer
    than their admission epochs (a torn read by another name).  The
    serving layer compacts between drains, when nothing is
    resident."""


def _emit(kind: str, **fields):
    from lux_tpu import telemetry
    telemetry.current().emit(kind, **fields)


@dataclasses.dataclass(frozen=True)
class WalRecord:
    epoch: int
    kind: int
    a: int
    b: int
    c: int


def _pack_record(epoch: int, kind: int, a: int, b: int, c: int,
                 prev_crc: int) -> bytes:
    body = np.array([epoch, kind, a, b, c],
                    luxfmt.V_DTYPE).tobytes()
    crc = chained_crc32(body, prev_crc)
    return body + np.array([crc], luxfmt.V_DTYPE).tobytes()


class MutationLog:
    """The CRC-chained append-only WAL (module docstring pillar 1).

    One instance owns an open append handle; each ``append_*`` writes
    one 24-byte record and fsyncs — durability is per record, so a
    crash between two records of a batch replays the durable prefix
    (the documented half-batch semantics).  ``replay`` is a
    classmethod: verify the chain, truncate a torn tail (emitting a
    ``wal_truncate`` telemetry event), raise typed MutationLogError
    on anything that cannot be a torn append."""

    def __init__(self, path: str, nv: int, capacity: int,
                 _resume: tuple | None = None):
        self.path = path
        self.nv = int(nv)
        self.capacity = int(capacity)
        if _resume is None:
            header = luxfmt.pack_wal_header(self.nv, self.capacity)
            try:
                fd = os.open(path,
                             os.O_WRONLY | os.O_CREAT | os.O_EXCL,
                             0o644)
            except FileExistsError:
                # the restart-after-crash path is the very situation
                # the WAL exists for — refuse typed, pointing at the
                # recovery entry, never an opaque builtin traceback
                raise MutationLogError(
                    path, "wal_exists",
                    "a mutation log already exists at this path — "
                    "a fresh log would orphan its durable history; "
                    "use LiveGraph.recover(g, path) to replay it, "
                    "or remove the file to start over") from None
            self._f = os.fdopen(fd, "wb")
            self._f.write(header)
            self._f.flush()
            os.fsync(self._f.fileno())
            self._crc = chained_crc32(header)
        else:
            size, crc = _resume
            self._f = open(path, "r+b")
            self._f.seek(size)
            self._crc = crc

    # -- append side ---------------------------------------------------

    def _append(self, record: bytes) -> None:
        self._f.write(record)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._crc = int(np.frombuffer(record, luxfmt.V_DTYPE)[5])

    def pack_edge(self, epoch: int, src: int, dst: int,
                  wbits: int) -> bytes:
        return _pack_record(epoch, REC_EDGE, src, dst, wbits,
                            self._crc)

    def append_edge(self, epoch: int, src: int, dst: int,
                    wbits: int) -> None:
        self._append(self.pack_edge(epoch, src, dst, wbits))

    def append_marker(self, epoch: int, kind: int, a: int,
                      b: int) -> None:
        self._append(_pack_record(epoch, kind, a, b, 0, self._crc))

    def write_torn(self, record: bytes) -> None:
        """Fault-injection hook (faults.MutationFaultPlan WAL_TORN):
        persist a STRICT PREFIX of ``record`` — what a power loss
        mid-append leaves on disk — and fsync it so the tear is
        really there for the replay to diagnose."""
        self._f.write(record[:len(record) // 2])
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass

    # -- replay / verify side ------------------------------------------

    @classmethod
    def scan(cls, path: str, nv: int | None = None):
        """Verify the whole log WITHOUT modifying it.  Returns
        (records, header_nv, capacity, torn_bytes): ``torn_bytes`` is
        the length of a recoverable torn tail (0 = clean); hard
        corruption raises MutationLogError.  scripts/fsck_lux.py's
        WAL leg and ``replay`` both run through here so the checker
        and the recovery path can never disagree on validity."""
        recs, hnv, cap, tail, _crc = cls._scan(path, nv=nv)
        return recs, hnv, cap, tail

    @classmethod
    def _scan(cls, path: str, nv: int | None = None):
        """scan + the final chain CRC (the resume seed), so replay
        never re-reads the file to recompute a chain the scan just
        walked."""
        with open(path, "rb") as f:
            blob = f.read()
        head = blob[:luxfmt.WAL_HEADER_SIZE]
        hnv, cap = luxfmt.read_wal_header(path, nv=nv, head=head)
        crc = chained_crc32(head)
        recs: list[WalRecord] = []
        off = luxfmt.WAL_HEADER_SIZE
        R = luxfmt.WAL_RECORD_SIZE
        last_epoch = 0
        bad_at = None
        while off + R <= len(blob):
            raw = blob[off:off + R]
            words = np.frombuffer(raw, luxfmt.V_DTYPE)
            want = chained_crc32(raw[:20], crc)
            if int(words[5]) != want:
                bad_at = off
                break
            epoch, kind = int(words[0]), int(words[1])
            if kind not in (REC_EDGE, REC_COMPACT_START,
                            REC_COMPACT_DONE):
                raise MutationLogError(
                    path, "record_kind",
                    f"record at byte {off} has unknown kind {kind} "
                    f"with a VALID chain CRC — log written by a "
                    f"newer/foreign build, refusing to replay")
            if epoch < last_epoch:
                raise MutationLogError(
                    path, "epoch_order",
                    f"record at byte {off} carries epoch {epoch} "
                    f"after epoch {last_epoch} — the monotone epoch "
                    f"counter never goes backwards; the log is "
                    f"corrupt or spliced")
            last_epoch = epoch
            recs.append(WalRecord(epoch, kind, int(words[2]),
                                  int(words[3]), int(words[4])))
            crc = int(words[5])
            off += R
        tail = len(blob) - off
        if bad_at is not None:
            # a torn append can only leave a STRICT PREFIX of the
            # record on disk (the writer's model: faults.WAL_TORN;
            # a complete record that landed carries its valid CRC) —
            # those never reach here (the loop stops short of a
            # partial record and reports them as ``tail``).  A
            # FULL-SIZE bad-CRC record is rot of a possibly-fsync-
            # acknowledged append, and one with further records
            # behind it is mid-file corruption — both must refuse,
            # never silently truncate an acknowledged mutation away
            behind = len(blob) - bad_at - R
            what = (f"with {behind} byte(s) of further records "
                    f"behind it — mid-file corruption"
                    if behind else
                    "at full record size — corruption of a "
                    "possibly-acknowledged final record")
            raise MutationLogError(
                path, "crc_chain",
                f"record at byte {bad_at} fails the CRC chain "
                f"{what}, not a torn append; refusing to replay")
        return recs, hnv, cap, tail, crc

    @classmethod
    def replay(cls, path: str, nv: int | None = None):
        """Crash-recovery entry: scan, TRUNCATE a torn tail in place
        (the pre-append state is the correct durable state — the torn
        record was never acknowledged), and return (records,
        truncated_bytes, resumable MutationLog open at the end)."""
        recs, hnv, cap, torn, crc = cls._scan(path, nv=nv)
        good = luxfmt.WAL_HEADER_SIZE + len(recs) * luxfmt.WAL_RECORD_SIZE
        if torn:
            with open(path, "r+b") as f:
                f.truncate(good)
                f.flush()
                os.fsync(f.fileno())
            _emit("wal_truncate", path=path, torn_bytes=int(torn),
                  records=len(recs))
        # the scan's final chain CRC IS the resume seed — no second
        # read of the file, no recomputed chain
        log = cls(path, hnv, cap, _resume=(good, crc))
        return recs, torn, log


# ---------------------------------------------------------------------
# the live graph


class LiveGraph:
    """Mutable graph = base generation + fixed-capacity delta blocks
    + monotone epochs (module docstring).  Thread contract: appends
    take the lock; readers snapshot ``(epoch, count)`` lock-free and
    epoch-mask — published slots are immutable and unwritten slots
    carry the EPOCH_SENTINEL, so a reader can never observe a torn
    slot regardless of interleaving."""

    def __init__(self, g: Graph, *, capacity: int = 1024,
                 wal_path: str | None = None,
                 fault=None, compact_threshold: float = 0.75,
                 _recovering: bool = False):
        if capacity < 1:
            raise ValueError(f"delta capacity {capacity} must be >= 1")
        if not 0.0 < compact_threshold <= 1.0:
            raise ValueError(f"compact_threshold {compact_threshold} "
                             f"must be in (0, 1]")
        self.origin = g               # recovery/oracle anchor
        self.base = g                 # current generation's base
        self.base_epoch = 0           # epoch folded into the base
        self.generation = 0
        self.epoch = 0                # latest published epoch
        self.capacity = int(capacity)
        self.weighted = g.weights is not None
        self.compact_threshold = float(compact_threshold)
        self.fault = fault
        self._lock = threading.Lock()
        self._fresh_delta()
        self.count = 0                # published delta slots
        self.pins = 0                 # RESIDENT queries on this gen
        self.admitted = 0             # admitted-but-unretired queries
        self.mutations = 0            # edges ever published
        self.compactions = 0
        self.peak_count = 0
        # full publish history [(src, dst, w, epoch)] — the
        # graph_at/oracle surface (O(total mutations) host memory;
        # diagnostic/test scope, module docstring)
        self._history: list[tuple] = []
        self._graph_cache: dict[int, Graph] = {}
        self._slot_cache: dict[int, tuple] = {}
        self._vslot_cache: dict[int, tuple] = {}  # geometry-keyed
        self._step_cache: dict[int, object] = {}
        self._wal: MutationLog | None = None
        if wal_path is not None and not _recovering:
            self._wal = MutationLog(wal_path, g.nv, capacity)

    def _fresh_delta(self) -> None:
        # FRESH arrays on every generation swap — a concurrent reader
        # holding the old arrays keeps a consistent published block
        # (immutable-once-published), never a zeroed-under-it one
        cap = self.capacity
        self.d_src = np.zeros(cap, np.int32)
        self.d_dst = np.zeros(cap, np.int32)
        self.d_w = np.zeros(cap, np.float32)
        self.d_epoch = np.full(cap, EPOCH_SENTINEL, np.int32)

    # -- ingest --------------------------------------------------------

    def append_edges(self, src, dst, weights=None) -> int:
        """Publish one mutation batch: WAL-journal then delta-publish
        each edge; the batch becomes ONE new epoch (visible the
        moment ``self.epoch`` advances, after every slot is fully
        written).  Returns the new epoch.  Raises DeltaFullError when
        the batch does not fit (the admission backpressure signal),
        MutationLogError/InjectedWorkerCrash from the fault plan's
        crash legs."""
        src = np.atleast_1d(np.asarray(src, np.int64))
        dst = np.atleast_1d(np.asarray(dst, np.int64))
        n = len(src)
        if n == 0:
            return self.epoch
        if len(dst) != n:
            raise ValueError(f"append_edges src/dst length mismatch "
                             f"({n} vs {len(dst)})")
        if self.weighted:
            if weights is None:
                raise ValueError("weighted live graph needs weights "
                                 "for every appended edge")
            w = np.atleast_1d(np.asarray(weights, np.float32))
            if len(w) != n:
                raise ValueError(
                    f"append_edges src/weights length mismatch "
                    f"({n} vs {len(w)})")
        else:
            if weights is not None:
                # Graph.with_edges refuses this same mismatch typed —
                # silently zeroing the caller's weight data would
                # journal 0.0 bits and serve hop-count semantics with
                # no signal that the weights vanished
                raise ValueError(
                    "append_edges got weights for an UNWEIGHTED live "
                    "graph — build the LiveGraph over a weighted "
                    "base, or drop the weights")
            w = np.zeros(n, np.float32)
        nv = self.base.nv
        if src.size and (int(src.max()) >= nv or int(src.min()) < 0
                         or int(dst.max()) >= nv or int(dst.min()) < 0):
            raise ValueError(f"appended edge endpoint outside "
                             f"[0, {nv})")
        with self._lock:
            if self.count + n > self.capacity:
                raise DeltaFullError(self.capacity)
            epoch = self.epoch + 1
            for i in range(n):
                s, d = int(src[i]), int(dst[i])
                wbits = int(np.float32(w[i]).view(np.uint32))
                if self.fault is not None:
                    record = (self._wal.pack_edge(epoch, s, d, wbits)
                              if self._wal is not None else b"")
                    self.fault.fire_append(self._wal, record)
                if self._wal is not None:
                    self._wal.append_edge(epoch, s, d, wbits)
                slot = self.count
                self.d_src[slot] = s
                self.d_dst[slot] = d
                self.d_w[slot] = w[i]
                # epoch LAST: a concurrent reader's epoch mask never
                # admits a half-written slot
                self.d_epoch[slot] = epoch
                self.count = slot + 1
                self._history.append((s, d, float(w[i]), epoch))
            self.mutations += n
            self.peak_count = max(self.peak_count, self.count)
            self.epoch = epoch
        # the wal path keys the events_summary CROSS-process
        # replay-regression audit: a crash and its recovery are
        # different processes, so the publisher's epochs and the
        # recovering wal_replay pair on the log path, not the run
        wal_kw = ({"wal": self._wal.path}
                  if self._wal is not None else {})
        _emit("mutation", edges=int(n), epoch=int(epoch),
              delta_count=int(self.count),
              occupancy=round(self.count / self.capacity, 4),
              **wal_kw)
        _emit("epoch_advance", from_epoch=int(epoch - 1),
              to_epoch=int(epoch), **wal_kw)
        return epoch

    def occupancy(self) -> float:
        return self.count / self.capacity

    # -- pins (snapshot isolation vs compaction) -----------------------

    def pin(self) -> None:
        with self._lock:
            self.pins += 1

    def unpin(self) -> None:
        with self._lock:
            self.pins = max(0, self.pins - 1)

    def admit(self, family: str | None = None) -> int | None:
        """Count one ADMITTED query and return the epoch it pins —
        ONE lock acquisition, so the stamp and the ledger entry are
        atomic (a mutate+compact between a separate read and a
        separate increment could fold the stamped view away before
        the ledger protected it).  Resident pins alone cannot
        protect a queued query: its epoch was pinned at admission,
        and a compaction before it reaches a column folds the delta
        out from under the OLD-base engines it will be served on — a
        wrong answer the torn-epoch audit is structurally blind to
        (answer_epoch == admission epoch both point at the vanished
        view).  The serving tier admits at submit and releases at
        exactly-once retirement/shed."""
        with self._lock:
            self.admitted += 1
            if family is None:
                return None
            return (self.epoch if family == "push"
                    else self.base_epoch)

    def release(self) -> None:
        with self._lock:
            self.admitted = max(0, self.admitted - 1)

    # -- epoch views ---------------------------------------------------

    def view_epoch(self, family: str = "push") -> int:
        """The epoch a newly admitted query of this engine family
        pins: push kinds see base + published delta (latest epoch);
        pull kinds see the base generation only (module docstring —
        no monotone revalidation exists for them, so their mutations
        become visible at compaction)."""
        return self.epoch if family == "push" else self.base_epoch

    def graph_at(self, epoch: int) -> Graph:
        """Host Graph as of ``epoch`` — the NumPy-oracle surface
        (origin + every published edge with epoch <= e; cached)."""
        if not 0 <= epoch <= self.epoch:
            raise ValueError(f"epoch {epoch} outside [0, "
                             f"{self.epoch}]")
        if epoch not in self._graph_cache:
            hist = [h for h in self._history if h[3] <= epoch]
            src = np.array([h[0] for h in hist], np.int64)
            dst = np.array([h[1] for h in hist], np.int64)
            w = (np.array([h[2] for h in hist], np.float32)
                 if self.weighted else None)
            self._graph_cache[epoch] = self.origin.with_edges(
                src, dst, w) if hist else self.origin
        return self._graph_cache[epoch]

    # -- delta relax (the device step; jit ARGUMENTS) ------------------

    @staticmethod
    def _evict_dead(cache: dict) -> None:
        """Drop entries whose weakref referent is gone.  The id()-
        keyed caches validate hits by weakref identity, but a dead
        geometry/engine's id may never be probed again (each
        refresh_live rebuilds engines at fresh addresses), so stale
        entries would accrete forever — O(nv) slot maps and compiled
        steps pinned per retired generation.  Run on every miss:
        the dicts hold a handful of live entries, so the sweep is
        O(live + newly dead)."""
        dead = [k for k, v in cache.items() if v[0]() is None]
        for k in dead:
            del cache[k]

    def _vertex_slots(self, sg) -> np.ndarray:
        """The O(nv) vertex -> padded-part-major-slot map for one
        shard geometry — depends only on the IMMUTABLE geometry
        (starts/vpad), never on the delta, so it is computed once per
        sg and survives every mutation batch and compaction —
        rebuilding it per batch would put O(nv) work (tens of MB of
        temporaries at RMAT25 scale) on the ingest hot path for a
        batch that touched a handful of slots."""
        key = id(sg)
        vs = self._vslot_cache.get(key)
        if vs is None or vs[0]() is not sg:
            self._evict_dead(self._vslot_cache)
            v = np.arange(sg.nv, dtype=np.int64)
            v_part = np.searchsorted(sg.starts, v, side="right") - 1
            v_slot = (v_part * sg.vpad
                      + (v - sg.starts[v_part])).astype(np.int32)
            vs = (weakref.ref(sg), v_slot)
            self._vslot_cache[key] = vs
        return vs[1]

    def delta_arrays(self, sg):
        """The fixed-capacity delta block TRANSLATED into ``sg``'s
        padded part-major slots, ready to pass as jit arguments:
        (src_slot i32 [cap], dst_slot i32 [cap], w f32 [cap],
        epoch i32 [cap]).  Published slots are immutable; per miss
        only O(capacity) translation work runs (the O(nv) vertex
        map is geometry-cached in ``_vertex_slots``) and the
        returned arrays are fresh copies (never aliases of the
        mutable tail)."""
        # keyed by id() but VALIDATED by a weakref identity check:
        # a dict key alone holds no reference, and CPython reuses a
        # freed object's address — a stale hit would translate slots
        # for a different shard geometry
        key = id(sg)
        cached = self._slot_cache.get(key)
        n = self.count
        if cached is None or cached[0]() is not sg \
                or cached[1] is not self.d_src or cached[2] < n:
            self._evict_dead(self._slot_cache)
            v_slot = self._vertex_slots(sg)
            src_slot = np.zeros(self.capacity, np.int32)
            dst_slot = np.full(self.capacity,
                               sg.num_parts * sg.vpad, np.int32)
            src_slot[:n] = v_slot[self.d_src[:n]]
            dst_slot[:n] = v_slot[self.d_dst[:n]]
            cached = (weakref.ref(sg), self.d_src, n, src_slot,
                      dst_slot, self.d_w.copy(), self.d_epoch.copy())
            self._slot_cache[key] = cached
        return cached[3], cached[4], cached[5], cached[6]

    def delta_step(self, eng):
        """The compiled delta-relax step for one push engine, CACHED
        per engine (keyed by id(), validated by weakref identity, dead
        entries evicted on miss) — every caller (revalidate, the serve
        runners' _apply_delta, register_audit) shares ONE compile per
        engine instead of re-inventing caching per site; a fresh
        jax.jit per call was the exact recompile-per-revalidate bug
        scripts/sweep_live.py found once already (PERF_NOTES round
        20)."""
        ent = self._step_cache.get(id(eng))
        if ent is None or ent[0]() is not eng:
            self._evict_dead(self._step_cache)
            step = self._build_delta_step(eng)
            self._step_cache[id(eng)] = (weakref.ref(eng), step)
        else:
            step = ent[1]
        return step

    def _build_delta_step(self, eng):
        """Delta-relax step for one push engine: (label
        [P, vpad(, B)], active, src_slot, dst_slot, w, epoch,
        col_epoch) -> (label, active, improved count).  ONE
        state-table gather (the delta-source fetch), candidates
        epoch-masked PER QUERY COLUMN to the reduce identity, then a
        scatter-min/max into the flat table; improvements come from a
        whole-table compare (no second gather), so the audit's
        gather budget holds at the dense iterations' own bound
        (audit.matrix_configs ``*_live_delta``).  The delta arrays
        are jit ARGUMENTS — appends never recompile."""
        import jax
        import jax.numpy as jnp

        prog = eng.program
        sg = eng.sg
        flat_n = sg.num_parts * sg.vpad
        reduce = prog.reduce
        if reduce not in ("min", "max"):
            raise ValueError(
                f"live delta relax requires a monotone min/max "
                f"program, got reduce={reduce!r} (pull kinds pin the "
                f"base generation instead — module docstring)")

        def step(label, active, src_slot, dst_slot, w, d_epoch,
                 col_epoch):
            ident = jnp.asarray(prog.identity, label.dtype)
            flat = label.reshape((flat_n,) + label.shape[2:])
            # weights pass RAW [cap] — the program's relax owns the
            # query-axis broadcast, exactly as in the dense iteration
            # (batched relax does w[..., None] itself)
            src_l = jnp.take(flat, src_slot, axis=0)
            cand = prog.relax(src_l, w if self.weighted else None)
            cand = jnp.where(src_l == ident, ident,
                             cand.astype(label.dtype))
            # per-column epoch mask: a column pinned to epoch e must
            # never see an edge published after it — the snapshot-
            # isolation contract, enforced inside the step
            mask = d_epoch.reshape(d_epoch.shape
                                   + (1,) * (cand.ndim - 1)) \
                <= col_epoch
            cand = jnp.where(mask, cand, ident)
            at = flat.at[dst_slot]
            new_flat = at.min(cand, mode="drop") if reduce == "min" \
                else at.max(cand, mode="drop")
            improved = new_flat != flat
            new_label = new_flat.reshape(label.shape)
            new_active = active | improved.reshape(active.shape)
            return new_label, new_active, \
                jnp.sum(improved.astype(jnp.int32))

        return jax.jit(step)

    def register_audit(self, eng) -> None:
        """Expose the delta-relax step to the static program auditor
        as an engine variant (engine/auditable.py) so the repo-wide
        matrix machine-checks its single state-table gather with the
        engine's own ProgramSpec."""
        import jax

        jitted = self.delta_step(eng)
        cap = self.capacity

        def _thunk():
            lab_sds, act_sds = eng._audit_state_sds
            i32 = np.int32
            col = (jax.ShapeDtypeStruct((lab_sds.shape[2],), i32)
                   if len(lab_sds.shape) > 2
                   else jax.ShapeDtypeStruct((), i32))
            return (lab_sds, act_sds,
                    jax.ShapeDtypeStruct((cap,), i32),
                    jax.ShapeDtypeStruct((cap,), i32),
                    jax.ShapeDtypeStruct((cap,), np.float32),
                    jax.ShapeDtypeStruct((cap,), i32), col)

        eng._register_variant("live_delta", jitted, _thunk)

    # -- incremental revalidation --------------------------------------

    def revalidate(self, eng, label, active, col_epoch=None):
        """Frontier-seeded incremental re-convergence of a converged
        state to this graph's published epoch (or per-column epochs):
        interleave the delta-relax step with the engine's compiled
        converge until the delta edges offer no further improvement —
        the fixed point of base + epoch-masked delta, reached by
        touching only the reachable-from-touched region (the
        incremental-vs-full sweep: scripts/sweep_live.py, PERF_NOTES
        round 20).  Returns (label, active, engine iterations)."""
        import jax
        import jax.numpy as jnp

        step = self.delta_step(eng)     # cached per engine
        args = self.delta_arrays(eng.sg)
        if col_epoch is None:
            col_epoch = self.epoch
        batched = getattr(eng.program, "batch", None)
        ce = (jnp.asarray(np.full(batched, col_epoch, np.int32))
              if batched is not None and np.ndim(col_epoch) == 0
              else jnp.asarray(np.asarray(col_epoch, np.int32)))
        total = 0
        while True:
            label, active, imp = step(label, active, *args, ce)
            if int(jax.device_get(imp)) == 0:
                break
            label, active, it = eng.converge(label, active)
            total += int(jax.device_get(it))
        return label, active, total

    # -- compaction ----------------------------------------------------

    def compact_economics(self) -> dict:
        """Price the standing delta drag against the one-time re-pack
        with the existing scalemodel terms: every dense boundary pays
        ~GATHER_SMALL_NS per delta slot for the delta-source fetch
        (the same per-edge gather rate the pair/page break-evens are
        priced from), while the re-pack is a host CSC rebuild over
        base+delta.  Compaction triggers when occupancy crosses
        ``compact_threshold`` — past it the fixed-capacity block is
        close enough to full that admission backpressure
        (DeltaFullError) threatens before the next natural quiet
        window."""
        from lux_tpu import scalemodel

        occ = self.occupancy()
        return {
            "occupancy": round(occ, 4),
            "threshold": self.compact_threshold,
            "should_compact": occ >= self.compact_threshold,
            "delta_count": int(self.count),
            "delta_drag_ns_per_boundary":
                round(self.count * scalemodel.GATHER_SMALL_NS, 1),
            "repack_edges": int(self.base.ne + self.count),
        }

    def should_compact(self) -> bool:
        return self.compact_economics()["should_compact"]

    def compact(self, force: bool = False):
        """Fold the published delta into a NEW base generation and
        swap atomically (module docstring pillar 4).  Returns the new
        generation number, or None when there is nothing to fold (or
        occupancy is under threshold and ``force`` is False).  Raises
        CompactPinnedError while queries pin the current generation —
        the serving layer compacts between drains.

        Holds the mutation lock END TO END.  The fold is ~40 ms
        (PERF_NOTES round 20) and a concurrent append in a released
        window would be lost twice over: its published slot silently
        discarded by the fresh-delta swap (in neither the new base
        nor the delta — wrong answers the torn-epoch audit cannot
        see), and its epoch-e+1 WAL record landing BEFORE this
        compaction's epoch-e START marker — a log that fails its own
        epoch_order validation, turning acknowledged durable
        mutations unrecoverable.  Ingest simply blocks for the fold
        (the backpressure-friendly choice); pin() takes the same
        lock, so the pin check cannot race either."""
        with self._lock:
            if self.pins or self.admitted:
                raise CompactPinnedError(
                    f"{self.pins} resident / {self.admitted} "
                    f"admitted query(ies) pin generation "
                    f"{self.generation}; drain before compacting")
            n = self.count
            epoch = self.epoch
            if n == 0 or (not force and not self.should_compact()):
                return None
            new_gen = self.generation + 1
            if self._wal is not None:
                self._wal.append_marker(epoch, REC_COMPACT_START, n,
                                        new_gen)
            _emit("compact_start", epoch=int(epoch),
                  generation=new_gen, delta_count=int(n),
                  occupancy=round(n / self.capacity, 4))
            if self.fault is not None:
                # the injected COMPACT_CRASH leg: die between the
                # START marker and the swap — recovery must come up
                # on the SURVIVING generation (base + published
                # delta)
                self.fault.fire_compact()
            new_base = self.base.with_edges(
                self.d_src[:n], self.d_dst[:n],
                self.d_w[:n] if self.weighted else None)
            self.base = new_base
            self.base_epoch = epoch
            self.generation = new_gen
            self._fresh_delta()
            self.count = 0
            self.compactions += 1
            self._slot_cache.clear()
            if self._wal is not None:
                self._wal.append_marker(epoch, REC_COMPACT_DONE,
                                        new_gen, epoch)
        _emit("compact_done", epoch=int(epoch), generation=new_gen,
              folded=int(n), ne=int(new_base.ne))
        return new_gen

    # -- recovery ------------------------------------------------------

    @classmethod
    def recover(cls, origin: Graph, wal_path: str, *,
                fault=None, compact_threshold: float = 0.75
                ) -> "LiveGraph":
        """Rebuild the live graph from the origin graph + the WAL:
        verify the chain (truncating a torn tail), replay every edge
        into the delta blocks, and re-fold every COMPLETED compaction
        (START..DONE pair) — deterministic CSC rebuilds, so the
        recovered generation is bitwise-identical to the pre-crash
        one.  A START without a DONE (COMPACT_CRASH) is ignored: the
        surviving generation is base + published delta, exactly what
        the log proves durable."""
        recs, torn, log = MutationLog.replay(wal_path, nv=origin.nv)
        lg = cls(origin, capacity=log.capacity, wal_path=wal_path,
                 fault=fault, compact_threshold=compact_threshold,
                 _recovering=True)
        lg._wal = log
        pending_start = None
        for rec in recs:
            if rec.kind == REC_EDGE:
                if lg.count >= lg.capacity:
                    raise MutationLogError(
                        wal_path, "capacity_overflow",
                        f"replay overflows the delta capacity "
                        f"{lg.capacity} with no compaction marker — "
                        f"log inconsistent with its own header")
                slot = lg.count
                lg.d_src[slot] = rec.a
                lg.d_dst[slot] = rec.b
                w = float(np.uint32(rec.c).view(np.float32))
                lg.d_w[slot] = w
                lg.d_epoch[slot] = rec.epoch
                lg.count = slot + 1
                lg._history.append((rec.a, rec.b, w, rec.epoch))
                lg.mutations += 1
                lg.peak_count = max(lg.peak_count, lg.count)
                lg.epoch = max(lg.epoch, rec.epoch)
            elif rec.kind == REC_COMPACT_START:
                pending_start = rec
            elif rec.kind == REC_COMPACT_DONE:
                if pending_start is None:
                    raise MutationLogError(
                        wal_path, "compact_pair",
                        f"COMPACT_DONE at epoch {rec.epoch} without "
                        f"a preceding COMPACT_START — the log's "
                        f"compaction bracket is broken")
                n = pending_start.a
                lg.base = lg.base.with_edges(
                    lg.d_src[:n], lg.d_dst[:n],
                    lg.d_w[:n] if lg.weighted else None)
                lg.base_epoch = rec.epoch
                lg.generation = rec.a
                # the surviving delta tail (appended after the fold's
                # snapshot) shifts down into a fresh block
                tail = lg.count - n
                ts, td = lg.d_src[n:lg.count].copy(), \
                    lg.d_dst[n:lg.count].copy()
                tw = lg.d_w[n:lg.count].copy()
                te = lg.d_epoch[n:lg.count].copy()
                lg._fresh_delta()
                lg.d_src[:tail], lg.d_dst[:tail] = ts, td
                lg.d_w[:tail], lg.d_epoch[:tail] = tw, te
                lg.count = tail
                lg.compactions += 1
                pending_start = None
        lg._slot_cache.clear()
        _emit("wal_replay", path=wal_path, records=len(recs),
              epoch=int(lg.epoch), generation=int(lg.generation),
              truncated_bytes=int(torn),
              delta_count=int(lg.count))
        return lg

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()


# ---------------------------------------------------------------------
# oracle verification of live-serving answers


def check_live_answers(live: LiveGraph, responses,
                       weighted: bool = False) -> int:
    """Verify serving responses against the NumPy oracles evaluated
    at each response's ADMISSION epoch (``graph_at``) — bitwise for
    the integer apps, the chaos acceptance's correctness bar.
    Returns the mismatch count."""
    from lux_tpu.apps import components, pagerank, sssp

    bad = 0
    for r in responses:
        epoch = r.epoch or 0
        g_e = live.graph_at(epoch)
        if r.kind == "sssp":
            ref = sssp.reference_sssp_batched(
                g_e, [r.source], weighted=weighted)[:, 0]
            if not weighted:
                ref = np.where(ref >= int(sssp.HOP_INF),
                               int(sssp.HOP_INF), ref)
                ok = np.array_equal(r.answer.astype(np.int64), ref)
            else:
                ok = bool(np.allclose(r.answer, ref))
        elif r.kind == "components":
            ref = components.reference_components_batched(
                g_e, [r.source])[:, 0]
            ok = np.array_equal(r.answer.astype(np.int64), ref)
        else:
            reset = pagerank.one_hot_resets(g_e.nv, [r.source])
            ref = pagerank.reference_pagerank_batched(
                g_e, reset, max(1, r.iters))[:, 0]
            ok = bool(np.allclose(r.answer, ref, atol=5e-5))
        if not ok:
            bad += 1
            print(f"LIVE MISMATCH qid={r.qid} kind={r.kind} "
                  f"source={r.source} epoch={epoch}")
    return bad
