"""Live graphs: crash-consistent mutation log, snapshot-isolated
epochs, incremental revalidation, and chaos-drilled compaction.

The reference is a static-graph batch system (its graphs are loaded
once and never mutate, reference pull_model.inl:253-320); the serving
tier built in rounds 14-18 answers live traffic against that frozen
snapshot.  This module makes the graph MUTABLE underneath the queries
with a robustness-first correctness story (ROADMAP item 4):

1. **Durable mutation log** (:class:`MutationLog`): every edge append
   is journaled to a CRC-CHAINED append-only WAL before it is
   visible — record i's CRC32 seeds from record i-1's
   (checkpoint.chained_crc32, the same per-leaf CRC discipline the
   checkpoints carry), so a torn mid-append write (power loss, the
   injected ``faults.WAL_TORN``) breaks the chain at the exact tear
   point.  Replay truncates a torn TAIL and recovers the precise
   pre-append state (bitwise — tests/test_livegraph.py); a broken
   chain FOLLOWED by further whole records cannot be a torn append
   and raises a typed :class:`MutationLogError` instead of replaying
   garbage.  The on-disk header format lives with the other formats
   (format.py ``read_wal_header``: magic/version/nv/capacity — a log
   from a DIFFERENT graph errors instead of replaying foreign
   mutations).

2. **Fixed-capacity delta blocks, snapshot-isolated epochs**:
   published mutations land in fixed-capacity host arrays
   (src/dst/weight/epoch) that are passed to the engines' delta-relax
   step as jit ARGUMENTS — no pair/page plan rebuild, no recompile,
   per append (the Ragged-Paged-Attention idiom from PAPERS.md:
   ragged growth through fixed-shape blocks).  Isolation is BY
   CONSTRUCTION: a published slot is never rewritten (compaction
   swaps in FRESH arrays rather than zeroing), the base generation's
   arrays are never mutated in place, unwritten slots carry an
   i32-max epoch sentinel written LAST — so a reader pinned to epoch
   e sees exactly the edges with ``d_epoch <= e`` no matter how the
   writer thread interleaves, and a torn read is impossible rather
   than merely unlikely.  ``epoch`` is a monotone counter advanced
   once per published append batch; scripts/events_summary.py FAILS
   any trail whose answers were computed at a different epoch than
   their admission pinned (the torn-epoch audit).

3. **Incremental revalidation** (:meth:`LiveGraph.revalidate`):
   frontier-seeded re-convergence — the delta-relax step gathers the
   delta sources from the state table (ONE state-table gather,
   machine-checked against the same audit gather budget as the dense
   iterations: lux_tpu/audit.py matrix configs ``*_live_delta``),
   relaxes the delta edges, epoch-masks per query column, scatters
   min/max into the table, and activates improved destinations; the
   push engine then re-converges only the reachable-from-touched
   region.  NumPy incremental oracles came FIRST per convention
   (apps/sssp.reference_sssp_incremental,
   components.reference_components_incremental) and the device path
   is proved equal to full recompute at the same epoch, bitwise for
   the integer apps.  Measured on CPU it beats full recompute across
   the touched-fraction sweep (scripts/sweep_live.py; PERF_NOTES
   round 20).  Round 21 extends the algebra past monotone appends:
   edge DELETIONS (:meth:`LiveGraph.delete_edges`) and WEIGHT
   UPDATES (:meth:`LiveGraph.reweight_edges`) journal as v2 WAL
   record kinds and publish TOMBSTONE/OVERWRITE delta slots (masked
   to the reduce identity by the delta relax — a monotone step
   cannot express them); revalidation past such an op dispatches to
   the ANTI-MONOTONE RE-SEED — compute the affected cone (forward
   reachability from the touched destinations, capped by
   ``cone_cap`` with a full-recompute fallback), re-seed it from the
   program's init labels, and re-converge over ``graph_at(epoch)``
   — proved equal to full recompute against the decremental oracles
   (apps/sssp.reference_sssp_decremental,
   components.reference_components_decremental), bitwise for the
   integer apps.

4. **Scheduled compaction** (:meth:`LiveGraph.compact`,
   :class:`CompactionScheduler`): the delta folds into the base
   layout via the shared deterministic ``_apply_ops`` construction
   (origin + full op history — the same rule graph_at and recover
   use, so live, oracle, and recovered bases are bitwise-identical)
   and the generation swaps ATOMICALLY under the lock: readers see
   the old (base, delta) pair or the new one, never a mixture.  The
   WAL brackets the fold with COMPACT_START/COMPACT_DONE markers; an
   injected crash between them (``faults.COMPACT_CRASH``) leaves a
   START without a DONE, and recovery comes up on the SURVIVING
   generation (origin base + full replay) — compaction is a LAYOUT
   transition, never a durability transition, so a half-built
   generation can always be discarded.  WHEN to fold is the
   scheduler's call (round 21): :meth:`compact_economics` prices the
   standing delta drag (MEASURED per-boundary samples from the serve
   runners when available, the scalemodel term otherwise) and the
   :class:`CompactionScheduler` weighs it against admission load,
   pending anti-monotone ops, and the fleet's SLO burn gauge —
   picking fold windows under live traffic instead of the old
   compact-between-drains heuristic.  Serving-tier backpressure:
   when ingest outruns compaction the delta blocks fill and
   mutations raise a typed :class:`DeltaFullError`, which the
   fleet's admission sheds as ``AdmissionError(reason="delta_full")``
   (lux_tpu/fleet.py).

Epoch visibility per engine family: the PUSH kinds (sssp /
components) see base + published delta at the latest epoch — their
monotone min/max programs absorb delta APPENDS exactly through the
delta-relax step.  The PULL kinds (pagerank) absorb appends through
the host-side base-generation + degree-correction step (serve.py
PullBatchRunner, round 21), so both families' admissions advance
with published epochs WITHOUT waiting for a fold.  The one cap is
anti-monotone: while a deletion/reweight is pending (not yet folded),
``view_epoch`` holds BOTH families at (earliest pending anti epoch -
1) — neither mechanism can express the op, so the op costs admission
FRESHNESS, never correctness.  Every pinning is recorded at
admission and audited at answer time (serve.py /
scripts/events_summary.py).

Durability scope: the WAL journals MUTATIONS; the base graph is the
caller's (a .lux file or a deterministic generator spec), so recovery
is ``LiveGraph.recover(origin_graph, wal_path)`` — replay the full
log onto the origin and re-fold any completed compactions
(deterministic, hence bitwise).  ``graph_at(epoch)`` materializes the
host Graph as of any epoch — the NumPy-oracle surface every
live-serving answer is checked against (O(total mutations) host
memory; a diagnostic/test surface, documented as such).
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import weakref

import numpy as np

from lux_tpu import format as luxfmt
from lux_tpu.checkpoint import chained_crc32
from lux_tpu.graph import Graph

# WAL record kinds (format.py owns the header; the 24-byte record
# layout is [epoch u32, kind u32, a u32, b u32, c u32, crc u32] with
# crc = chained_crc32(first 20 bytes, prev record's crc; the chain
# seeds from the header's CRC so a re-headered log cannot re-validate)
REC_EDGE = 1           # a=src, b=dst, c=float32 weight bits
REC_COMPACT_START = 2  # a=delta count folded, b=new generation
REC_COMPACT_DONE = 3   # a=new generation, b=base epoch after fold
# v2 record kinds (round 21, the full mutation algebra).  The record
# LAYOUT is unchanged, so a v1 log replays bitwise under this reader;
# a v2 kind inside a v1-headered log is typed record_kind corruption
# (the kind set is part of the header version's contract).
REC_DELETE = 4         # a=src, b=dst, c=0
REC_REWEIGHT = 5       # a=src, b=dst, c=new float32 weight bits

_V1_KINDS = frozenset((REC_EDGE, REC_COMPACT_START, REC_COMPACT_DONE))
_V2_KINDS = _V1_KINDS | {REC_DELETE, REC_REWEIGHT}
_KINDS_BY_VERSION = {1: _V1_KINDS, 2: _V2_KINDS}

# delta-slot kinds (the d_kind column).  A published DELETE/REWEIGHT
# slot is a TOMBSTONE/OVERWRITE marker: it consumes a delta slot (so
# occupancy prices it and DeltaFullError backpressure covers it) but
# the monotone delta-relax step masks it to the reduce identity — its
# effect reaches answers only through the anti-monotone admission cap
# (view_epoch) + re-seed / compaction fold, never through a monotone
# relax that cannot express it.
DK_APPEND = 0
DK_DELETE = 1
DK_REWEIGHT = 2

_REC_BY_OP = {"append": REC_EDGE, "delete": REC_DELETE,
              "reweight": REC_REWEIGHT}
_DK_BY_OP = {"append": DK_APPEND, "delete": DK_DELETE,
             "reweight": DK_REWEIGHT}
_OP_BY_REC = {REC_EDGE: "append", REC_DELETE: "delete",
              REC_REWEIGHT: "reweight"}

# unwritten delta slots carry this epoch sentinel (written LAST in a
# slot publish) so a concurrent reader's epoch mask can never see a
# half-written slot — the torn-read-free-by-construction invariant
EPOCH_SENTINEL = np.int32(np.iinfo(np.int32).max)

# nominal host prices for the pointer-structured live consumers
# (memory_terms, round 22): CPython has no portable exact size for a
# list-of-tuples or a Counter entry, so the unified ledger prices the
# DOCUMENTED nominal per entry — a 5-tuple history op (~tuple header
# + 5 boxed fields + list slot) and a Counter entry (~dict slot +
# key 2-tuple + two boxed ints).  What matters observably is the
# O(count) growth these make visible, not malloc jitter; the NumPy
# oracle re-derives the same formula bitwise.
HISTORY_ENTRY_BYTES = 112
MULTISET_ENTRY_BYTES = 96


class LiveGraphError(RuntimeError):
    """Base of the live-graph subsystem's typed failures."""


class MutationLogError(LiveGraphError):
    """The mutation log failed verification.  Carries ``path``,
    ``check`` (torn_tail / crc_chain / epoch_order / record_kind /
    compact_pair / capacity_overflow / wal_exists) and ``detail`` —
    the same typed-diagnosis shape as
    format.GraphFormatError, consumed by scripts/fsck_lux.py (exit
    2).  ``torn_tail`` is the RECOVERABLE class: replay truncates it;
    every other check is hard corruption that must never replay."""

    def __init__(self, path: str, check: str, detail: str):
        super().__init__(f"{path}: mutation log [{check}] — {detail}")
        self.path = path
        self.check = check
        self.detail = detail


class DeltaFullError(LiveGraphError):
    """The fixed-capacity delta blocks are full: ingest has outrun
    compaction.  The serving tier's admission converts this into the
    typed ``AdmissionError(reason="delta_full")`` backpressure shed
    (lux_tpu/fleet.py) instead of blocking or silently dropping."""

    def __init__(self, capacity: int):
        super().__init__(
            f"delta blocks full ({capacity} slots): compact before "
            f"appending more mutations")
        self.capacity = capacity


class CompactPinnedError(LiveGraphError):
    """compact() was called while queries still pin the current
    generation — swapping under them would un-mask base edges newer
    than their admission epochs (a torn read by another name).  The
    serving layer compacts between drains, when nothing is
    resident."""


def _emit(kind: str, **fields):
    from lux_tpu import telemetry
    telemetry.current().emit(kind, **fields)


@dataclasses.dataclass(frozen=True)
class WalRecord:
    epoch: int
    kind: int
    a: int
    b: int
    c: int


def _pack_record(epoch: int, kind: int, a: int, b: int, c: int,
                 prev_crc: int) -> bytes:
    body = np.array([epoch, kind, a, b, c],
                    luxfmt.V_DTYPE).tobytes()
    crc = chained_crc32(body, prev_crc)
    return body + np.array([crc], luxfmt.V_DTYPE).tobytes()


class MutationLog:
    """The CRC-chained append-only WAL (module docstring pillar 1).

    One instance owns an open append handle; each ``append_*`` writes
    one 24-byte record and fsyncs — durability is per record, so a
    crash between two records of a batch replays the durable prefix
    (the documented half-batch semantics).  ``replay`` is a
    classmethod: verify the chain, truncate a torn tail (emitting a
    ``wal_truncate`` telemetry event), raise typed MutationLogError
    on anything that cannot be a torn append."""

    def __init__(self, path: str, nv: int, capacity: int,
                 version: int = luxfmt.WAL_VERSION,
                 _resume: tuple | None = None):
        self.path = path
        self.nv = int(nv)
        self.capacity = int(capacity)
        self.version = int(version)
        self.records = 0        # records appended THROUGH this handle
        if _resume is None:
            header = luxfmt.pack_wal_header(self.nv, self.capacity,
                                            version=self.version)
            try:
                fd = os.open(path,
                             os.O_WRONLY | os.O_CREAT | os.O_EXCL,
                             0o644)
            except FileExistsError:
                # the restart-after-crash path is the very situation
                # the WAL exists for — refuse typed, pointing at the
                # recovery entry, never an opaque builtin traceback
                raise MutationLogError(
                    path, "wal_exists",
                    "a mutation log already exists at this path — "
                    "a fresh log would orphan its durable history; "
                    "use LiveGraph.recover(g, path) to replay it, "
                    "or remove the file to start over") from None
            self._f = os.fdopen(fd, "wb")
            self._f.write(header)
            self._f.flush()
            os.fsync(self._f.fileno())
            self._crc = chained_crc32(header)
        else:
            size, crc = _resume
            self._f = open(path, "r+b")
            self._f.seek(size)
            self._crc = crc

    # -- append side ---------------------------------------------------

    def _append(self, record: bytes) -> None:
        self._f.write(record)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._crc = int(np.frombuffer(record, luxfmt.V_DTYPE)[5])
        self.records += 1

    def buffer_bytes(self) -> int:
        """Bytes the open append handle accounts for in the unified
        byte ledger (lux_tpu/memwatch.py, round 22): the header plus
        every record appended through THIS handle — the page-cache /
        stream-buffer footprint of the append path.  Per-record fsync
        keeps the userspace buffer empty, so this is an upper bound
        on dirty bytes and exact on what the handle wrote."""
        return (luxfmt.WAL_HEADER_SIZE
                + self.records * luxfmt.WAL_RECORD_SIZE)

    def pack_edge(self, epoch: int, src: int, dst: int,
                  wbits: int) -> bytes:
        return _pack_record(epoch, REC_EDGE, src, dst, wbits,
                            self._crc)

    def pack_mutation(self, epoch: int, op: str, src: int, dst: int,
                      wbits: int) -> bytes:
        """Pack one mutation record of any op (append / delete /
        reweight) against the CURRENT chain position — the
        fault-injection hook (WAL_TORN) needs the exact bytes the
        append would write."""
        kind = _REC_BY_OP[op]
        if kind not in _KINDS_BY_VERSION[self.version]:
            raise MutationLogError(
                self.path, "record_kind",
                f"op {op!r} (record kind {kind}) is not in the "
                f"v{self.version} header's kind set — recover into a "
                f"fresh v{luxfmt.WAL_VERSION} log to use the full "
                f"mutation algebra")
        return _pack_record(epoch, kind, src, dst, wbits, self._crc)

    def append_edge(self, epoch: int, src: int, dst: int,
                    wbits: int) -> None:
        self._append(self.pack_edge(epoch, src, dst, wbits))

    def append_mutation(self, epoch: int, op: str, src: int,
                        dst: int, wbits: int) -> None:
        self._append(self.pack_mutation(epoch, op, src, dst, wbits))

    def append_marker(self, epoch: int, kind: int, a: int,
                      b: int) -> None:
        self._append(_pack_record(epoch, kind, a, b, 0, self._crc))

    def write_torn(self, record: bytes) -> None:
        """Fault-injection hook (faults.MutationFaultPlan WAL_TORN):
        persist a STRICT PREFIX of ``record`` — what a power loss
        mid-append leaves on disk — and fsync it so the tear is
        really there for the replay to diagnose."""
        self._f.write(record[:len(record) // 2])
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass

    # -- replay / verify side ------------------------------------------

    @classmethod
    def scan(cls, path: str, nv: int | None = None):
        """Verify the whole log WITHOUT modifying it.  Returns
        (records, header_nv, capacity, torn_bytes): ``torn_bytes`` is
        the length of a recoverable torn tail (0 = clean); hard
        corruption raises MutationLogError.  scripts/fsck_lux.py's
        WAL leg and ``replay`` both run through here so the checker
        and the recovery path can never disagree on validity."""
        recs, hnv, cap, tail, _crc, _ver = cls._scan(path, nv=nv)
        return recs, hnv, cap, tail

    @classmethod
    def _scan(cls, path: str, nv: int | None = None):
        """scan + the final chain CRC (the resume seed), so replay
        never re-reads the file to recompute a chain the scan just
        walked."""
        with open(path, "rb") as f:
            blob = f.read()
        head = blob[:luxfmt.WAL_HEADER_SIZE]
        hnv, cap, ver = luxfmt.read_wal_header(path, nv=nv, head=head)
        known = _KINDS_BY_VERSION[ver]
        crc = chained_crc32(head)
        recs: list[WalRecord] = []
        off = luxfmt.WAL_HEADER_SIZE
        R = luxfmt.WAL_RECORD_SIZE
        last_epoch = 0
        bad_at = None
        while off + R <= len(blob):
            raw = blob[off:off + R]
            words = np.frombuffer(raw, luxfmt.V_DTYPE)
            want = chained_crc32(raw[:20], crc)
            if int(words[5]) != want:
                bad_at = off
                break
            epoch, kind = int(words[0]), int(words[1])
            if kind not in known:
                extra = (f" (a v2 mutation kind inside a v{ver} "
                         f"header — the kind set is part of the "
                         f"version contract)"
                         if kind in _V2_KINDS else
                         " — log written by a newer/foreign build")
                raise MutationLogError(
                    path, "record_kind",
                    f"record at byte {off} has kind {kind} outside "
                    f"the v{ver} kind set with a VALID chain CRC"
                    f"{extra}, refusing to replay")
            if epoch < last_epoch:
                raise MutationLogError(
                    path, "epoch_order",
                    f"record at byte {off} carries epoch {epoch} "
                    f"after epoch {last_epoch} — the monotone epoch "
                    f"counter never goes backwards; the log is "
                    f"corrupt or spliced")
            last_epoch = epoch
            recs.append(WalRecord(epoch, kind, int(words[2]),
                                  int(words[3]), int(words[4])))
            crc = int(words[5])
            off += R
        tail = len(blob) - off
        if bad_at is not None:
            # a torn append can only leave a STRICT PREFIX of the
            # record on disk (the writer's model: faults.WAL_TORN;
            # a complete record that landed carries its valid CRC) —
            # those never reach here (the loop stops short of a
            # partial record and reports them as ``tail``).  A
            # FULL-SIZE bad-CRC record is rot of a possibly-fsync-
            # acknowledged append, and one with further records
            # behind it is mid-file corruption — both must refuse,
            # never silently truncate an acknowledged mutation away
            behind = len(blob) - bad_at - R
            what = (f"with {behind} byte(s) of further records "
                    f"behind it — mid-file corruption"
                    if behind else
                    "at full record size — corruption of a "
                    "possibly-acknowledged final record")
            raise MutationLogError(
                path, "crc_chain",
                f"record at byte {bad_at} fails the CRC chain "
                f"{what}, not a torn append; refusing to replay")
        return recs, hnv, cap, tail, crc, ver

    @classmethod
    def replay(cls, path: str, nv: int | None = None):
        """Crash-recovery entry: scan, TRUNCATE a torn tail in place
        (the pre-append state is the correct durable state — the torn
        record was never acknowledged), and return (records,
        truncated_bytes, resumable MutationLog open at the end)."""
        recs, hnv, cap, torn, crc, ver = cls._scan(path, nv=nv)
        good = luxfmt.WAL_HEADER_SIZE + len(recs) * luxfmt.WAL_RECORD_SIZE
        if torn:
            with open(path, "r+b") as f:
                f.truncate(good)
                f.flush()
                os.fsync(f.fileno())
            _emit("wal_truncate", path=path, torn_bytes=int(torn),
                  records=len(recs))
        # the scan's final chain CRC IS the resume seed — no second
        # read of the file, no recomputed chain.  The resumed log
        # keeps the HEADER'S version: appends to a recovered v1 log
        # stay within the v1 kind set (pack_mutation refuses typed).
        log = cls(path, hnv, cap, version=ver, _resume=(good, crc))
        return recs, torn, log


# ---------------------------------------------------------------------
# the live graph


def _apply_ops(origin: Graph, ops, weighted: bool) -> Graph:
    """Deterministic host construction of origin + a mutation-op
    prefix ``[(op, src, dst, w, epoch), ...]`` — the ONE targeting
    rule every fold surface shares (graph_at, compact, recover), so
    the live view, the compacted base, and the recovered base are
    bitwise-identical by construction.

    Targeting: a delete/reweight of (s, d) hits the FIRST surviving
    base edge in dst-sorted ``edge_arrays`` order, else the first
    live appended edge (publish order).  The pure-append prefix
    reduces to exactly ``Graph.with_edges``'s construction (same
    concatenation into ``from_edges``), so pre-algebra logs fold
    bitwise-identically to the round-20 code."""
    if not ops:
        return origin
    base_src, base_dst = origin.edge_arrays()
    base_w = (np.asarray(origin.weights, np.float32).copy()
              if weighted else None)
    alive = np.ones(origin.ne, dtype=bool)
    app_src: list = []
    app_dst: list = []
    app_w: list = []
    app_alive: list = []
    base_ix: dict = {}
    app_ix: dict = {}
    if any(h[0] != "append" for h in ops):
        for i, sd in enumerate(zip(base_src.tolist(),
                                   base_dst.tolist())):
            base_ix.setdefault(sd, []).append(i)
    for h in ops:
        op, s, d, w = h[0], int(h[1]), int(h[2]), h[3]
        if op == "append":
            app_ix.setdefault((s, d), []).append(len(app_src))
            app_src.append(s)
            app_dst.append(d)
            app_w.append(np.float32(w))
            app_alive.append(True)
            continue
        tgt = next((i for i in base_ix.get((s, d), ())
                    if alive[i]), None)
        if op == "delete":
            if tgt is not None:
                alive[tgt] = False
            else:
                j = next(i for i in app_ix.get((s, d), ())
                         if app_alive[i])
                app_alive[j] = False
        else:  # reweight
            if tgt is not None:
                base_w[tgt] = np.float32(w)
            else:
                j = next(i for i in app_ix.get((s, d), ())
                         if app_alive[i])
                app_w[j] = np.float32(w)
    keep = [i for i, ok in enumerate(app_alive) if ok]
    src = np.concatenate([base_src[alive],
                          np.array([app_src[i] for i in keep],
                                   np.int64)])
    dst = np.concatenate([base_dst[alive],
                          np.array([app_dst[i] for i in keep],
                                   np.int64)])
    w_all = None
    if weighted:
        w_all = np.concatenate([base_w[alive],
                                np.array([app_w[i] for i in keep],
                                         np.float32)])
    return Graph.from_edges(src, dst, origin.nv, weights=w_all)


class LiveGraph:
    """Mutable graph = base generation + fixed-capacity delta blocks
    + monotone epochs (module docstring).  Thread contract: appends
    take the lock; readers snapshot ``(epoch, count)`` lock-free and
    epoch-mask — published slots are immutable and unwritten slots
    carry the EPOCH_SENTINEL, so a reader can never observe a torn
    slot regardless of interleaving."""

    def __init__(self, g: Graph, *, capacity: int = 1024,
                 wal_path: str | None = None,
                 fault=None, compact_threshold: float = 0.75,
                 cone_cap: float = 0.5,
                 _recovering: bool = False):
        if capacity < 1:
            raise ValueError(f"delta capacity {capacity} must be >= 1")
        if not 0.0 < compact_threshold <= 1.0:
            raise ValueError(f"compact_threshold {compact_threshold} "
                             f"must be in (0, 1]")
        if not 0.0 < cone_cap <= 1.0:
            raise ValueError(f"cone_cap {cone_cap} must be in (0, 1]")
        self.origin = g               # recovery/oracle anchor
        self.base = g                 # current generation's base
        self.base_epoch = 0           # epoch folded into the base
        self.generation = 0
        self.epoch = 0                # latest published epoch
        self.capacity = int(capacity)
        self.weighted = g.weights is not None
        self.compact_threshold = float(compact_threshold)
        self.cone_cap = float(cone_cap)
        self.fault = fault
        self._lock = threading.Lock()
        self._fresh_delta()
        self.count = 0                # published delta slots
        self.pins = 0                 # RESIDENT queries on this gen
        self.admitted = 0             # admitted-but-unretired queries
        self.mutations = 0            # mutations ever published
        self.deletions = 0            # deletion ops ever published
        self.reweights = 0            # reweight ops ever published
        self.reseeds = 0              # anti-monotone re-seeds run
        self.reseed_fallbacks = 0     # ... that fell back to full
        self.compactions = 0
        self.peak_count = 0
        # full publish history [(op, src, dst, w, epoch)] — the
        # graph_at/oracle surface (O(total mutations) host memory;
        # diagnostic/test scope, module docstring)
        self._history: list[tuple] = []
        # pending ANTI-MONOTONE ops [(epoch, op, src, dst)] not yet
        # folded into the base — while nonempty, view_epoch caps
        # admission at (min anti epoch - 1) for BOTH families: a
        # monotone delta relax cannot express a deletion/reweight, so
        # serving past it would answer BELOW/ABOVE the true fixed
        # point, the error class the torn-epoch audit is blind to.
        self._anti: list[tuple] = []
        # measured per-slot delta drag samples (ns), fed by the serve
        # runners (record_drag_sample) for the scheduler's economics
        # — bounded deque, newest-biased median
        self._drag_samples = collections.deque(maxlen=64)
        # live-edge multiset (src, dst) -> count, built LAZILY on the
        # first anti-monotone mutation (delete/reweight of an edge
        # that does not exist must refuse typed BEFORE journaling)
        self._edge_counts = None
        self._graph_cache: dict[int, Graph] = {}
        self._slot_cache: dict[int, tuple] = {}
        self._vslot_cache: dict[int, tuple] = {}  # geometry-keyed
        self._step_cache: dict[int, object] = {}
        self._wal: MutationLog | None = None
        if wal_path is not None and not _recovering:
            self._wal = MutationLog(wal_path, g.nv, capacity)

    def _fresh_delta(self) -> None:
        # FRESH arrays on every generation swap — a concurrent reader
        # holding the old arrays keeps a consistent published block
        # (immutable-once-published), never a zeroed-under-it one
        cap = self.capacity
        self.d_src = np.zeros(cap, np.int32)
        self.d_dst = np.zeros(cap, np.int32)
        self.d_w = np.zeros(cap, np.float32)
        self.d_kind = np.zeros(cap, np.int32)   # DK_APPEND default
        self.d_epoch = np.full(cap, EPOCH_SENTINEL, np.int32)

    # -- ingest --------------------------------------------------------

    def _check_pair(self, src, dst, what: str):
        """Shared shape/endpoint validation for every mutation op."""
        src = np.atleast_1d(np.asarray(src, np.int64))
        dst = np.atleast_1d(np.asarray(dst, np.int64))
        n = len(src)
        if len(dst) != n:
            raise ValueError(f"{what} src/dst length mismatch "
                             f"({n} vs {len(dst)})")
        nv = self.base.nv
        if src.size and (int(src.max()) >= nv or int(src.min()) < 0
                         or int(dst.max()) >= nv or int(dst.min()) < 0):
            raise ValueError(f"{what}: edge endpoint outside "
                             f"[0, {nv})")
        return src, dst, n

    def _live_edge_counts(self):
        """The (src, dst) -> live-multiplicity multiset, built LAZILY
        on the first anti-monotone mutation and maintained
        incrementally by ``_publish`` afterwards — a delete/reweight
        of an edge that does not exist must refuse typed BEFORE the
        WAL journals anything (a journaled phantom op would replay on
        every recovery)."""
        if self._edge_counts is None:
            src, dst = self.origin.edge_arrays()
            counts = collections.Counter(
                zip(src.tolist(), dst.tolist()))
            for h in self._history:
                if h[0] == "append":
                    counts[(h[1], h[2])] += 1
                elif h[0] == "delete":
                    counts[(h[1], h[2])] -= 1
            self._edge_counts = counts
        return self._edge_counts

    def _publish(self, op: str, src, dst, w) -> int:
        """Shared publish core for every mutation op (WAL journal ->
        delta slot -> epoch advance); callers validated shapes,
        weights, and endpoints.  The batch becomes ONE new epoch
        (visible the moment ``self.epoch`` advances, after every slot
        is fully written).  Anti-monotone existence validation runs
        HERE, under the same lock as the journal write — a check in
        the caller could race a concurrent delete of the same edge."""
        n = len(src)
        dk = _DK_BY_OP[op]
        with self._lock:
            if self.count + n > self.capacity:
                raise DeltaFullError(self.capacity)
            if dk != DK_APPEND:
                counts = self._live_edge_counts()
                need = collections.Counter(
                    zip(src.tolist(), dst.tolist()))
                for (s, d), k in need.items():
                    # deletions CONSUME multiplicity; reweights of the
                    # same edge restate it (last wins), needing one
                    required = k if dk == DK_DELETE else 1
                    have = counts[(s, d)]
                    if have < required:
                        raise ValueError(
                            f"{op} of edge ({s}, {d}): {have} live "
                            f"edge(s), batch needs {required} — "
                            f"refusing before journaling (mutations "
                            f"of phantom edges would replay on every "
                            f"recovery)")
            epoch = self.epoch + 1
            for i in range(n):
                s, d = int(src[i]), int(dst[i])
                wbits = int(np.float32(w[i]).view(np.uint32))
                if self.fault is not None:
                    record = (self._wal.pack_mutation(
                        epoch, op, s, d, wbits)
                        if self._wal is not None else b"")
                    self.fault.fire_append(self._wal, record, op=op)
                if self._wal is not None:
                    self._wal.append_mutation(epoch, op, s, d, wbits)
                slot = self.count
                self.d_src[slot] = s
                self.d_dst[slot] = d
                self.d_w[slot] = w[i]
                self.d_kind[slot] = dk
                # epoch LAST: a concurrent reader's epoch mask never
                # admits a half-written slot
                self.d_epoch[slot] = epoch
                self.count = slot + 1
                self._history.append((op, s, d, float(w[i]), epoch))
                if self._edge_counts is not None:
                    if op == "append":
                        self._edge_counts[(s, d)] += 1
                    elif op == "delete":
                        self._edge_counts[(s, d)] -= 1
                if dk != DK_APPEND:
                    self._anti.append((epoch, op, s, d))
            self.mutations += n
            if op == "delete":
                self.deletions += n
            elif op == "reweight":
                self.reweights += n
            self.peak_count = max(self.peak_count, self.count)
            self.epoch = epoch
        # the wal path keys the events_summary CROSS-process
        # replay-regression audit: a crash and its recovery are
        # different processes, so the publisher's epochs and the
        # recovering wal_replay pair on the log path, not the run
        wal_kw = ({"wal": self._wal.path}
                  if self._wal is not None else {})
        _emit("mutation", op=op, edges=int(n), epoch=int(epoch),
              delta_count=int(self.count),
              occupancy=round(self.count / self.capacity, 4),
              **wal_kw)
        _emit("epoch_advance", from_epoch=int(epoch - 1),
              to_epoch=int(epoch), **wal_kw)
        return epoch

    def append_edges(self, src, dst, weights=None) -> int:
        """Publish one edge-append batch: WAL-journal then
        delta-publish each edge; the batch becomes ONE new epoch.
        Returns the new epoch.  Raises DeltaFullError when the batch
        does not fit (the admission backpressure signal),
        MutationLogError/InjectedWorkerCrash from the fault plan's
        crash legs."""
        src, dst, n = self._check_pair(src, dst, "append_edges")
        if n == 0:
            return self.epoch
        if self.weighted:
            if weights is None:
                raise ValueError("weighted live graph needs weights "
                                 "for every appended edge")
            w = np.atleast_1d(np.asarray(weights, np.float32))
            if len(w) != n:
                raise ValueError(
                    f"append_edges src/weights length mismatch "
                    f"({n} vs {len(w)})")
        else:
            if weights is not None:
                # Graph.with_edges refuses this same mismatch typed —
                # silently zeroing the caller's weight data would
                # journal 0.0 bits and serve hop-count semantics with
                # no signal that the weights vanished
                raise ValueError(
                    "append_edges got weights for an UNWEIGHTED live "
                    "graph — build the LiveGraph over a weighted "
                    "base, or drop the weights")
            w = np.zeros(n, np.float32)
        return self._publish("append", src, dst, w)

    def delete_edges(self, src, dst) -> int:
        """Publish one edge-DELETION batch (round 21, the mutation
        algebra).  Each (src, dst) tombstones exactly ONE live edge —
        the first surviving base edge in dst-sorted order, else the
        first live appended edge (the deterministic targeting rule
        ``_apply_ops`` shares between graph_at, compaction, and
        recovery, so every surface folds the same edge away).
        Deleting an edge that does not exist raises ValueError BEFORE
        the WAL journals anything.  Deletions are ANTI-MONOTONE: the
        published tombstone slot consumes delta capacity but is
        masked to the reduce identity by the delta-relax step; its
        effect reaches answers through the ``view_epoch`` admission
        cap and the re-seed (:meth:`revalidate`) / compaction fold.
        NumPy oracles: apps/sssp.reference_sssp_decremental,
        apps/components.reference_components_decremental.  Returns
        the new epoch."""
        src, dst, n = self._check_pair(src, dst, "delete_edges")
        if n == 0:
            return self.epoch
        return self._publish("delete", src, dst,
                             np.zeros(n, np.float32))

    def reweight_edges(self, src, dst, weights) -> int:
        """Publish one edge WEIGHT-UPDATE batch (round 21).  Targets
        one live edge per (src, dst) under the same deterministic
        rule as :meth:`delete_edges`; reweighting a phantom edge or
        an UNWEIGHTED live graph refuses typed before journaling.
        Conservatively ANTI-MONOTONE for BOTH engine families: a
        weight increase can raise converged sssp distances (which a
        monotone min-relax can never repair), and rather than
        special-case the decrease-only direction the admission cap +
        re-seed path covers every reweight — the safe-over-clever
        choice the chaos drill can actually falsify.  Returns the new
        epoch."""
        if not self.weighted:
            raise ValueError(
                "reweight_edges on an UNWEIGHTED live graph — "
                "hop-count semantics have no weights to update; "
                "build the LiveGraph over a weighted base")
        src, dst, n = self._check_pair(src, dst, "reweight_edges")
        if n == 0:
            return self.epoch
        if weights is None:
            raise ValueError("reweight_edges needs the new weights")
        w = np.atleast_1d(np.asarray(weights, np.float32))
        if len(w) != n:
            raise ValueError(
                f"reweight_edges src/weights length mismatch "
                f"({n} vs {len(w)})")
        return self._publish("reweight", src, dst, w)

    def occupancy(self) -> float:
        return self.count / self.capacity

    def memory_terms(self) -> dict:
        """The live graph's host/device byte terms for the unified
        per-replica ledger (lux_tpu/memwatch.py, round 22) — the
        consumers rounds 20-21 built but never priced.  Every term is
        a deterministic integer so the ledger's NumPy oracle can
        re-derive it independently and match bitwise:

        - ``live_delta``: the five preallocated delta-block arrays
          (src/dst/w/kind/epoch, 20 B per capacity slot) — actual
          ``nbytes``, priced at construction not occupancy, because
          the allocation IS capacity-sized.
        - ``live_history``: the full publish history list, nominal
          HISTORY_ENTRY_BYTES per op (a 5-tuple + list slot; host
          pointer structures have no exact portable size, so the
          ledger prices the documented nominal — O(total mutations)
          growth is the thing to see, not malloc jitter).
        - ``live_multiset``: the lazily-built (src, dst) -> count
          Counter, nominal MULTISET_ENTRY_BYTES per distinct edge,
          ZERO until the first anti-monotone mutation builds it —
          the step change is visible in the trail.
        - ``live_wal``: the open append handle's written bytes
          (MutationLog.buffer_bytes), 0 without a WAL."""
        delta = (self.d_src.nbytes + self.d_dst.nbytes
                 + self.d_w.nbytes + self.d_kind.nbytes
                 + self.d_epoch.nbytes)
        return {
            "live_delta": int(delta),
            "live_history": len(self._history) * HISTORY_ENTRY_BYTES,
            "live_multiset": (0 if self._edge_counts is None
                              else len(self._edge_counts)
                              * MULTISET_ENTRY_BYTES),
            "live_wal": (0 if self._wal is None
                         else self._wal.buffer_bytes()),
        }

    # -- pins (snapshot isolation vs compaction) -----------------------

    def pin(self) -> None:
        with self._lock:
            self.pins += 1

    def unpin(self) -> None:
        with self._lock:
            self.pins = max(0, self.pins - 1)

    def admit(self, family: str | None = None) -> int | None:
        """Count one ADMITTED query and return the epoch it pins —
        ONE lock acquisition, so the stamp and the ledger entry are
        atomic (a mutate+compact between a separate read and a
        separate increment could fold the stamped view away before
        the ledger protected it).  Resident pins alone cannot
        protect a queued query: its epoch was pinned at admission,
        and a compaction before it reaches a column folds the delta
        out from under the OLD-base engines it will be served on — a
        wrong answer the torn-epoch audit is structurally blind to
        (answer_epoch == admission epoch both point at the vanished
        view).  The serving tier admits at submit and releases at
        exactly-once retirement/shed."""
        with self._lock:
            self.admitted += 1
            if family is None:
                return None
            return self.view_epoch(family)

    def release(self) -> None:
        with self._lock:
            self.admitted = max(0, self.admitted - 1)

    # -- epoch views ---------------------------------------------------

    def anti_pending(self) -> int:
        """Count of published anti-monotone ops (deletions/reweights)
        not yet folded into the base — while nonzero, ``view_epoch``
        caps admission below the earliest one."""
        return len(self._anti)

    def view_epoch(self, family: str = "push") -> int:
        """The epoch a newly admitted query of this engine family
        pins.  Both families now advance with published epochs — push
        kinds absorb appends through the delta-relax step, pull kinds
        through the host-side degree/delta correction (serve.py
        PullBatchRunner, round 21) — EXCEPT past a pending
        anti-monotone op: a deletion/reweight cannot be expressed by
        either mechanism, so admission is capped at (earliest pending
        anti epoch - 1) until a re-seed-bearing fold publishes it.
        Answers stay exact at their admitted epoch; anti-monotone
        mutations cost admission FRESHNESS, never correctness."""
        # snapshot FIRST: checking self._anti and then iterating it
        # races compact()'s under-lock clear — a fold landing between
        # the truthiness gate and the min() raised ValueError on the
        # emptied list (found by lockcheck snapshot-iteration,
        # regression: tests/test_lockcheck.py)
        anti = list(self._anti)
        if anti:
            return min(t[0] for t in anti) - 1
        return self.epoch

    def graph_at(self, epoch: int) -> Graph:
        """Host Graph as of ``epoch`` — the NumPy-oracle surface
        (origin + every published mutation with epoch <= e, applied
        by ``_apply_ops``; cached)."""
        if not 0 <= epoch <= self.epoch:
            raise ValueError(f"epoch {epoch} outside [0, "
                             f"{self.epoch}]")
        if epoch not in self._graph_cache:
            # list() snapshot: _publish appends under the lock while
            # oracle threads replay history lock-free
            hist = [h for h in list(self._history) if h[4] <= epoch]
            self._graph_cache[epoch] = _apply_ops(
                self.origin, hist, self.weighted)
        return self._graph_cache[epoch]

    # -- delta relax (the device step; jit ARGUMENTS) ------------------

    @staticmethod
    def _evict_dead(cache: dict) -> None:
        """Drop entries whose weakref referent is gone.  The id()-
        keyed caches validate hits by weakref identity, but a dead
        geometry/engine's id may never be probed again (each
        refresh_live rebuilds engines at fresh addresses), so stale
        entries would accrete forever — O(nv) slot maps and compiled
        steps pinned per retired generation.  Run on every miss:
        the dicts hold a handful of live entries, so the sweep is
        O(live + newly dead)."""
        dead = [k for k, v in cache.items() if v[0]() is None]
        for k in dead:
            del cache[k]

    def _vertex_slots(self, sg) -> np.ndarray:
        """The O(nv) vertex -> padded-part-major-slot map for one
        shard geometry — depends only on the IMMUTABLE geometry
        (starts/vpad), never on the delta, so it is computed once per
        sg and survives every mutation batch and compaction —
        rebuilding it per batch would put O(nv) work (tens of MB of
        temporaries at RMAT25 scale) on the ingest hot path for a
        batch that touched a handful of slots."""
        key = id(sg)
        vs = self._vslot_cache.get(key)
        if vs is None or vs[0]() is not sg:
            self._evict_dead(self._vslot_cache)
            v = np.arange(sg.nv, dtype=np.int64)
            v_part = np.searchsorted(sg.starts, v, side="right") - 1
            v_slot = (v_part * sg.vpad
                      + (v - sg.starts[v_part])).astype(np.int32)
            vs = (weakref.ref(sg), v_slot)
            self._vslot_cache[key] = vs
        return vs[1]

    def delta_arrays(self, sg):
        """The fixed-capacity delta block TRANSLATED into ``sg``'s
        padded part-major slots, ready to pass as jit arguments:
        (src_slot i32 [cap], dst_slot i32 [cap], w f32 [cap],
        kind i32 [cap], epoch i32 [cap]).  Published slots are
        immutable; per miss only O(capacity) translation work runs
        (the O(nv) vertex map is geometry-cached in
        ``_vertex_slots``) and the returned arrays are fresh copies
        (never aliases of the mutable tail)."""
        # keyed by id() but VALIDATED by a weakref identity check:
        # a dict key alone holds no reference, and CPython reuses a
        # freed object's address — a stale hit would translate slots
        # for a different shard geometry
        key = id(sg)
        cached = self._slot_cache.get(key)
        n = self.count
        if cached is None or cached[0]() is not sg \
                or cached[1] is not self.d_src or cached[2] < n:
            self._evict_dead(self._slot_cache)
            v_slot = self._vertex_slots(sg)
            src_slot = np.zeros(self.capacity, np.int32)
            dst_slot = np.full(self.capacity,
                               sg.num_parts * sg.vpad, np.int32)
            src_slot[:n] = v_slot[self.d_src[:n]]
            dst_slot[:n] = v_slot[self.d_dst[:n]]
            cached = (weakref.ref(sg), self.d_src, n, src_slot,
                      dst_slot, self.d_w.copy(), self.d_kind.copy(),
                      self.d_epoch.copy())
            # lockcheck: allow(guarded-field) idempotent cache fill
            # (last-writer-wins over immutable published slots);
            # compact()'s under-lock clear targets a generation the
            # engines must refresh_live() past before serving anyway
            self._slot_cache[key] = cached
        return cached[3], cached[4], cached[5], cached[6], cached[7]

    def append_deltas(self):
        """Host view of the published APPEND slots — (src i64, dst
        i64, w f32, epoch i32) with tombstone/overwrite slots
        filtered out.  The pull runners' host-side correction surface
        (serve.PullBatchRunner, round 21): published slots are
        immutable and ``count`` is advanced after the slot's epoch
        lands, so a lock-free snapshot here is consistent by the same
        construction the device delta arrays rely on."""
        n = self.count
        m = self.d_kind[:n] == DK_APPEND
        return (self.d_src[:n][m].astype(np.int64),
                self.d_dst[:n][m].astype(np.int64),
                self.d_w[:n][m].copy(), self.d_epoch[:n][m].copy())

    def delta_step(self, eng):
        """The compiled delta-relax step for one push engine, CACHED
        per engine (keyed by id(), validated by weakref identity, dead
        entries evicted on miss) — every caller (revalidate, the serve
        runners' _apply_delta, register_audit) shares ONE compile per
        engine instead of re-inventing caching per site; a fresh
        jax.jit per call was the exact recompile-per-revalidate bug
        scripts/sweep_live.py found once already (PERF_NOTES round
        20)."""
        ent = self._step_cache.get(id(eng))
        if ent is None or ent[0]() is not eng:
            self._evict_dead(self._step_cache)
            step = self._build_delta_step(eng)
            self._step_cache[id(eng)] = (weakref.ref(eng), step)
        else:
            step = ent[1]
        return step

    def _build_delta_step(self, eng):
        """Delta-relax step for one push engine: (label
        [P, vpad(, B)], active, src_slot, dst_slot, w, kind, epoch,
        col_epoch) -> (label, active, improved count).  ONE
        state-table gather (the delta-source fetch), candidates
        epoch-masked PER QUERY COLUMN to the reduce identity, then a
        scatter-min/max into the flat table; improvements come from a
        whole-table compare (no second gather), so the audit's
        gather budget holds at the dense iterations' own bound
        (audit.matrix_configs ``*_live_delta``).  The delta arrays
        are jit ARGUMENTS — appends never recompile.  Tombstone and
        reweight slots (``kind != DK_APPEND``) are masked to the
        reduce identity: a monotone relax cannot express them, so
        they flow to answers only through the view_epoch admission
        cap + re-seed/fold (module docstring)."""
        import jax
        import jax.numpy as jnp

        prog = eng.program
        sg = eng.sg
        flat_n = sg.num_parts * sg.vpad
        reduce = prog.reduce
        if reduce not in ("min", "max"):
            raise ValueError(
                f"live delta relax requires a monotone min/max "
                f"program, got reduce={reduce!r} (pull kinds use the "
                f"host-side degree correction instead — serve.py)")

        def step(label, active, src_slot, dst_slot, w, d_kind,
                 d_epoch, col_epoch):
            ident = jnp.asarray(prog.identity, label.dtype)
            flat = label.reshape((flat_n,) + label.shape[2:])
            # weights pass RAW [cap] — the program's relax owns the
            # query-axis broadcast, exactly as in the dense iteration
            # (batched relax does w[..., None] itself)
            src_l = jnp.take(flat, src_slot, axis=0)
            cand = prog.relax(src_l, w if self.weighted else None)
            cand = jnp.where(src_l == ident, ident,
                             cand.astype(label.dtype))
            # per-column epoch mask: a column pinned to epoch e must
            # never see an edge published after it — the snapshot-
            # isolation contract, enforced inside the step.  The kind
            # mask drops anti-monotone slots the same way.
            mask = d_epoch.reshape(d_epoch.shape
                                   + (1,) * (cand.ndim - 1)) \
                <= col_epoch
            mask = mask & (d_kind == DK_APPEND).reshape(
                d_kind.shape + (1,) * (cand.ndim - 1))
            cand = jnp.where(mask, cand, ident)
            at = flat.at[dst_slot]
            new_flat = at.min(cand, mode="drop") if reduce == "min" \
                else at.max(cand, mode="drop")
            improved = new_flat != flat
            new_label = new_flat.reshape(label.shape)
            new_active = active | improved.reshape(active.shape)
            return new_label, new_active, \
                jnp.sum(improved.astype(jnp.int32))

        return jax.jit(step)

    def register_audit(self, eng) -> None:
        """Expose the delta-relax step to the static program auditor
        as an engine variant (engine/auditable.py) so the repo-wide
        matrix machine-checks its single state-table gather with the
        engine's own ProgramSpec."""
        import jax

        jitted = self.delta_step(eng)
        cap = self.capacity

        def _thunk():
            lab_sds, act_sds = eng._audit_state_sds
            i32 = np.int32
            col = (jax.ShapeDtypeStruct((lab_sds.shape[2],), i32)
                   if len(lab_sds.shape) > 2
                   else jax.ShapeDtypeStruct((), i32))
            return (lab_sds, act_sds,
                    jax.ShapeDtypeStruct((cap,), i32),
                    jax.ShapeDtypeStruct((cap,), i32),
                    jax.ShapeDtypeStruct((cap,), np.float32),
                    jax.ShapeDtypeStruct((cap,), i32),
                    jax.ShapeDtypeStruct((cap,), i32), col)

        eng._register_variant("live_delta", jitted, _thunk)

    # -- incremental revalidation --------------------------------------

    def revalidate(self, eng, label, active, col_epoch=None):
        """Frontier-seeded incremental re-convergence of a converged
        state to this graph's published epoch (or per-column epochs):
        interleave the delta-relax step with the engine's compiled
        converge until the delta edges offer no further improvement —
        the fixed point of base + epoch-masked delta, reached by
        touching only the reachable-from-touched region (the
        incremental-vs-full sweep: scripts/sweep_live.py, PERF_NOTES
        round 20).  Returns (label, active, engine iterations).

        When a pending ANTI-MONOTONE op (deletion/reweight) falls at
        or before the target epoch, dispatches to the cone re-seed
        path instead (round 21): ``eng`` must then be built over
        ``graph_at(target)`` — the monotone delta relax cannot
        express the op against the old base — and ``col_epoch`` must
        be a scalar (per-column targets cannot cross an anti epoch;
        typed LiveGraphError).  NumPy oracles:
        apps/sssp.reference_sssp_decremental,
        apps/components.reference_components_decremental."""
        import jax
        import jax.numpy as jnp

        if col_epoch is None:
            col_epoch = self.epoch
        anti_min = min((t[0] for t in list(self._anti)), default=None)
        if np.ndim(col_epoch) == 0:
            if anti_min is not None and anti_min <= int(col_epoch):
                return self._revalidate_anti(eng, label, active,
                                             int(col_epoch))
        elif anti_min is not None \
                and anti_min <= int(np.max(col_epoch)):
            raise LiveGraphError(
                f"per-column revalidation cannot cross the pending "
                f"anti-monotone epoch {anti_min} — the re-seed needs "
                f"ONE target epoch; call revalidate with a scalar "
                f"col_epoch and an engine built over graph_at(epoch)")
        step = self.delta_step(eng)     # cached per engine
        args = self.delta_arrays(eng.sg)
        batched = getattr(eng.program, "batch", None)
        ce = (jnp.asarray(np.full(batched, col_epoch, np.int32))
              if batched is not None and np.ndim(col_epoch) == 0
              else jnp.asarray(np.asarray(col_epoch, np.int32)))
        total = 0
        while True:
            label, active, imp = step(label, active, *args, ce)
            if int(jax.device_get(imp)) == 0:
                break
            label, active, it = eng.converge(label, active)
            total += int(jax.device_get(it))
        return label, active, total

    def _revalidate_anti(self, eng, label, active, target: int):
        """The anti-monotone RE-SEED (round 21): compute the affected
        cone — forward reachability over ``graph_at(target)`` from
        every pending anti op's destination — re-seed those vertices
        to the program's init labels on the host, re-activate
        everything, and run the engine's compiled converge to the
        exact fixed point.  Correctness (mirrors the decremental
        oracles' argument): a vertex whose fixed point degrades is
        reachable in the new graph from some touched destination
        (the suffix of its stale witness path past the LAST mutated
        edge survives), so it is in the cone and restarts from init;
        every other vertex starts on the monotone side of its fixed
        point — the relax converges to full recompute's answer,
        bitwise for the integer apps (tests/test_livegraph.py).

        A cone larger than ``cone_cap * nv`` falls back to a full
        recompute from ``init_state`` (at that size the incremental
        path has no work left to skip — scripts/sweep_live.py round
        21 locates the crossover).  CONTRACT: ``eng`` is built over
        ``graph_at(target)``."""
        import jax
        import jax.numpy as jnp

        sg = eng.sg
        g_new = self.graph_at(target)
        if sg.nv != g_new.nv:
            raise LiveGraphError(
                f"re-seed engine geometry nv={sg.nv} does not match "
                f"graph_at({target}).nv={g_new.nv}")
        src, dst = g_new.edge_arrays()
        cone = np.zeros(g_new.nv, dtype=bool)
        touched = [d for (e, _op, _s, d) in list(self._anti)
                   if e <= target]
        cone[np.asarray(touched, np.int64)] = True
        while True:
            add = np.zeros(g_new.nv, dtype=bool)
            add[dst[cone[src]]] = True
            add &= ~cone
            if not add.any():
                break
            cone |= add
        cone_n = int(cone.sum())
        fallback = cone_n > self.cone_cap * g_new.nv
        batched = getattr(eng.program, "batch", None)
        if fallback:
            label, active = eng.init_state()
        else:
            init_lab, _ = eng.program.init(sg)
            lab_host = sg.from_padded(
                np.asarray(jax.device_get(label)))
            init_host = sg.from_padded(np.asarray(init_lab))
            cmask = cone if batched is None else cone[:, None]
            new_host = np.where(cmask, init_host, lab_host)
            # full-True active on the REAL vertices (to_padded zero-
            # fills the padding lanes, keeping them inactive): the
            # converge must also propagate append improvements into
            # the untouched region, not only repair the cone
            ones = np.ones((g_new.nv,) if batched is None
                           else (g_new.nv, batched), bool)
            label, active = eng.place(sg.to_padded(new_host),
                                      sg.to_padded(ones))
        if self.fault is not None:
            # RESEED_CRASH: die between the cone computation and the
            # converge — recovery must come up with the anti ops
            # still pending (admission stays capped; no answer was
            # produced from the half-re-seeded state)
            self.fault.fire_reseed()
        label, active, it = eng.converge(label, active)
        self.reseeds += 1
        if fallback:
            self.reseed_fallbacks += 1
        wal_kw = ({"wal": self._wal.path}
                  if self._wal is not None else {})
        _emit("reseed", epoch=int(target), cone=cone_n,
              cone_frac=round(cone_n / g_new.nv, 4),
              fallback=bool(fallback), anti=len(touched), **wal_kw)
        return label, active, int(jax.device_get(it))

    # -- compaction ----------------------------------------------------

    def record_drag_sample(self, seconds: float, count: int) -> None:
        """Feed one MEASURED delta-drag sample — a fenced timing of a
        delta-relax boundary over ``count`` published slots (the
        serve runners sample every Nth ``_apply_delta``).  The
        scheduler's economics prefer the measured median over the
        scalemodel term (``drag_source="measured"``): the modeled
        GATHER_SMALL_NS rate is a small-table calibration and the
        live table may sit past the 64-128 MB emitter step
        (PERF_NOTES)."""
        if count <= 0 or seconds <= 0:
            return
        self._drag_samples.append(seconds * 1e9 / count)

    def compact_economics(self) -> dict:
        """Price the standing delta drag against the one-time re-pack.
        Every dense boundary pays ~drag_ns per delta slot for the
        delta-source fetch — the scalemodel GATHER_SMALL_NS term
        until measured samples arrive (``record_drag_sample``), then
        the measured per-slot median (``drag_source``) — while the
        re-pack is a host CSC rebuild over base+delta.  The legacy
        trigger (``should_compact``) fires when occupancy crosses
        ``compact_threshold``; the round-21
        :class:`CompactionScheduler` folds in anti-monotone pressure,
        admission load, and SLO burn on top of these terms."""
        from lux_tpu import scalemodel

        occ = self.occupancy()
        modeled = self.count * scalemodel.GATHER_SMALL_NS
        if self._drag_samples:
            per_slot = float(np.median(np.fromiter(
                self._drag_samples, np.float64)))
            drag, source = per_slot * self.count, "measured"
        else:
            drag, source = modeled, "modeled"
        return {
            "occupancy": round(occ, 4),
            "threshold": self.compact_threshold,
            "should_compact": occ >= self.compact_threshold,
            "delta_count": int(self.count),
            "anti_pending": len(self._anti),
            "delta_drag_ns_per_boundary": round(drag, 1),
            "modeled_drag_ns_per_boundary": round(modeled, 1),
            "drag_source": source,
            "drag_samples": len(self._drag_samples),
            "repack_edges": int(self.base.ne + self.count),
        }

    def should_compact(self) -> bool:
        return self.compact_economics()["should_compact"]

    def compact(self, force: bool = False):
        """Fold the published delta into a NEW base generation and
        swap atomically (module docstring pillar 4).  Returns the new
        generation number, or None when there is nothing to fold (or
        occupancy is under threshold and ``force`` is False).  Raises
        CompactPinnedError while queries pin the current generation —
        the serving layer compacts between drains.

        Holds the mutation lock END TO END.  The fold is ~40 ms
        (PERF_NOTES round 20) and a concurrent append in a released
        window would be lost twice over: its published slot silently
        discarded by the fresh-delta swap (in neither the new base
        nor the delta — wrong answers the torn-epoch audit cannot
        see), and its epoch-e+1 WAL record landing BEFORE this
        compaction's epoch-e START marker — a log that fails its own
        epoch_order validation, turning acknowledged durable
        mutations unrecoverable.  Ingest simply blocks for the fold
        (the backpressure-friendly choice); pin() takes the same
        lock, so the pin check cannot race either."""
        with self._lock:
            if self.pins or self.admitted:
                raise CompactPinnedError(
                    f"{self.pins} resident / {self.admitted} "
                    f"admitted query(ies) pin generation "
                    f"{self.generation}; drain before compacting")
            n = self.count
            epoch = self.epoch
            if n == 0 or (not force and not self.should_compact()):
                return None
            new_gen = self.generation + 1
            if self._wal is not None:
                self._wal.append_marker(epoch, REC_COMPACT_START, n,
                                        new_gen)
            _emit("compact_start", epoch=int(epoch),
                  generation=new_gen, delta_count=int(n),
                  occupancy=round(n / self.capacity, 4))
            if self.fault is not None:
                # the injected COMPACT_CRASH leg: die between the
                # START marker and the swap — recovery must come up
                # on the SURVIVING generation (base + published
                # delta)
                self.fault.fire_compact()
            # fold from the ORIGIN through the full op history — the
            # same _apply_ops construction graph_at and recover use,
            # so live base, oracle surface, and recovered base are
            # bitwise-identical (for a pure-append history this is
            # exactly the old base.with_edges(delta) concatenation)
            new_base = _apply_ops(
                self.origin,
                [h for h in self._history if h[4] <= epoch],
                self.weighted)
            self.base = new_base
            self.base_epoch = epoch
            self.generation = new_gen
            self._fresh_delta()
            self.count = 0
            self.compactions += 1
            # every published anti op is <= epoch — the fold just
            # materialized them, so admission advances again
            self._anti = [t for t in self._anti if t[0] > epoch]
            self._slot_cache.clear()
            if self._wal is not None:
                self._wal.append_marker(epoch, REC_COMPACT_DONE,
                                        new_gen, epoch)
        _emit("compact_done", epoch=int(epoch), generation=new_gen,
              folded=int(n), ne=int(new_base.ne))
        return new_gen

    # -- recovery ------------------------------------------------------

    @classmethod
    def recover(cls, origin: Graph, wal_path: str, *,
                fault=None, compact_threshold: float = 0.75
                ) -> "LiveGraph":
        """Rebuild the live graph from the origin graph + the WAL:
        verify the chain (truncating a torn tail), replay every edge
        into the delta blocks, and re-fold every COMPLETED compaction
        (START..DONE pair) — deterministic CSC rebuilds, so the
        recovered generation is bitwise-identical to the pre-crash
        one.  A START without a DONE (COMPACT_CRASH) is ignored: the
        surviving generation is base + published delta, exactly what
        the log proves durable."""
        recs, torn, log = MutationLog.replay(wal_path, nv=origin.nv)
        lg = cls(origin, capacity=log.capacity, wal_path=wal_path,
                 fault=fault, compact_threshold=compact_threshold,
                 _recovering=True)
        lg._wal = log
        pending_start = None
        for rec in recs:
            if rec.kind in (REC_EDGE, REC_DELETE, REC_REWEIGHT):
                if lg.count >= lg.capacity:
                    raise MutationLogError(
                        wal_path, "capacity_overflow",
                        f"replay overflows the delta capacity "
                        f"{lg.capacity} with no compaction marker — "
                        f"log inconsistent with its own header")
                op = _OP_BY_REC[rec.kind]
                slot = lg.count
                lg.d_src[slot] = rec.a
                lg.d_dst[slot] = rec.b
                w = float(np.uint32(rec.c).view(np.float32))
                lg.d_w[slot] = w
                lg.d_kind[slot] = _DK_BY_OP[op]
                lg.d_epoch[slot] = rec.epoch
                lg.count = slot + 1
                lg._history.append((op, rec.a, rec.b, w, rec.epoch))
                lg.mutations += 1
                if op == "delete":
                    lg.deletions += 1
                    lg._anti.append((rec.epoch, op, rec.a, rec.b))
                elif op == "reweight":
                    lg.reweights += 1
                    lg._anti.append((rec.epoch, op, rec.a, rec.b))
                lg.peak_count = max(lg.peak_count, lg.count)
                lg.epoch = max(lg.epoch, rec.epoch)
            elif rec.kind == REC_COMPACT_START:
                pending_start = rec
            elif rec.kind == REC_COMPACT_DONE:
                if pending_start is None:
                    raise MutationLogError(
                        wal_path, "compact_pair",
                        f"COMPACT_DONE at epoch {rec.epoch} without "
                        f"a preceding COMPACT_START — the log's "
                        f"compaction bracket is broken")
                n = pending_start.a
                # refold from the ORIGIN through the replayed history
                # — the same _apply_ops construction compact ran, so
                # the recovered generation is bitwise-identical
                fold_epoch = rec.b
                lg.base = _apply_ops(
                    lg.origin,
                    [h for h in lg._history if h[4] <= fold_epoch],
                    lg.weighted)
                lg.base_epoch = fold_epoch
                lg.generation = rec.a
                # the surviving delta tail (appended after the fold's
                # snapshot) shifts down into a fresh block
                tail = lg.count - n
                ts, td = lg.d_src[n:lg.count].copy(), \
                    lg.d_dst[n:lg.count].copy()
                tw = lg.d_w[n:lg.count].copy()
                tk = lg.d_kind[n:lg.count].copy()
                te = lg.d_epoch[n:lg.count].copy()
                lg._fresh_delta()
                lg.d_src[:tail], lg.d_dst[:tail] = ts, td
                lg.d_w[:tail], lg.d_epoch[:tail] = tw, te
                lg.d_kind[:tail] = tk
                lg.count = tail
                lg.compactions += 1
                lg._anti = [t for t in lg._anti
                            if t[0] > fold_epoch]
                pending_start = None
        lg._slot_cache.clear()
        _emit("wal_replay", path=wal_path, records=len(recs),
              epoch=int(lg.epoch), generation=int(lg.generation),
              truncated_bytes=int(torn),
              delta_count=int(lg.count))
        return lg

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()


# ---------------------------------------------------------------------
# the compaction scheduler


class CompactionScheduler:
    """Economics-driven compaction scheduling under LIVE traffic
    (round 21) — replaces the serving tier's compact-between-drains
    occupancy heuristic.  ``decide()`` is a pure policy read over the
    live graph's :meth:`LiveGraph.compact_economics` (measured delta
    drag when the serve runners have fed samples), the admission
    ledger, and an optional SLO burn gauge (the fleet's
    error-budget burn, lux_tpu/fleet.py); ``maybe_compact()`` acts on
    it, respecting the pin/admission refusal rules (a
    CompactPinnedError race demotes the decision to a deferral, never
    an error).

    Decision order (first match wins):

    1. empty           -> none   (nothing published, nothing pending)
    2. admitted/pinned -> defer  (never fold a view out from under an
                                  admitted query — the wrong-answer
                                  class the torn-epoch audit is blind
                                  to)
    3. slo_burn        -> defer  (burn gauge over ``burn_max`` while
                                  occupancy still has headroom: the
                                  fold's ingest stall would feed the
                                  burn — back off unless the delta is
                                  nearly full, where DeltaFullError
                                  sheds loom larger)
    4. anti_monotone   -> compact (pending deletions/reweights cap
                                  admission freshness at every epoch
                                  they wait — fold at the first quiet
                                  window)
    5. occupancy       -> compact (past ``compact_threshold``,
                                  DeltaFullError backpressure
                                  threatens)
    6. drag            -> compact (standing per-boundary delta drag —
                                  measured median preferred — exceeds
                                  ``drag_budget_ns``)
    7. idle            -> none

    Every compact decision emits a ``compact_scheduled`` event
    carrying the economics that justified it
    (scripts/events_summary.py audits the trail: a scheduler
    compaction without its economics FAILS)."""

    def __init__(self, live: LiveGraph, *, burn=None,
                 burn_max: float = 0.5,
                 drag_budget_ns: float = 4096.0):
        self.live = live
        self.burn = burn              # callable -> current SLO burn
        self.burn_max = float(burn_max)
        self.drag_budget_ns = float(drag_budget_ns)
        self.scheduler_compactions = 0
        self.deferrals = 0

    def decide(self) -> dict:
        lv = self.live
        eco = lv.compact_economics()
        burn = float(self.burn()) if self.burn is not None else 0.0
        base = {
            "occupancy": eco["occupancy"],
            "threshold": eco["threshold"],
            "delta_count": eco["delta_count"],
            "anti_pending": eco["anti_pending"],
            "drag_ns": eco["delta_drag_ns_per_boundary"],
            "drag_source": eco["drag_source"],
            "admitted": int(lv.admitted),
            "pins": int(lv.pins),
            "burn": round(burn, 4),
        }
        if lv.count == 0 and not lv._anti:
            return {"action": "none", "reason": "empty", **base}
        if lv.pins or lv.admitted:
            self.deferrals += 1
            return {"action": "defer", "reason": "admitted", **base}
        if burn > self.burn_max and eco["occupancy"] < 0.9:
            self.deferrals += 1
            return {"action": "defer", "reason": "slo_burn", **base}
        if lv._anti:
            reason = "anti_monotone"
        elif eco["occupancy"] >= eco["threshold"]:
            reason = "occupancy"
        elif eco["delta_drag_ns_per_boundary"] >= self.drag_budget_ns:
            reason = "drag"
        else:
            return {"action": "none", "reason": "idle", **base}
        decision = {"action": "compact", "reason": reason, **base}
        _emit("compact_scheduled", **decision)
        return decision

    def maybe_compact(self, server=None) -> dict:
        """Run one scheduling step: decide, and on a compact decision
        fold + (when given the serving ``server``) refresh its
        engines onto the new generation.  A pin/admission race
        between decide and the fold demotes to a deferral."""
        decision = self.decide()
        if decision["action"] != "compact":
            return decision
        try:
            gen = self.live.compact(force=True)
        except CompactPinnedError:
            self.deferrals += 1
            return dict(decision, action="defer", reason="pin_race")
        if gen is not None:
            self.scheduler_compactions += 1
            if server is not None:
                server.refresh_live()
        return dict(decision, generation=gen)


# ---------------------------------------------------------------------
# oracle verification of live-serving answers


def check_live_answers(live: LiveGraph, responses,
                       weighted: bool = False) -> int:
    """Verify serving responses against the NumPy oracles evaluated
    at each response's ADMISSION epoch (``graph_at``) — bitwise for
    the integer apps, the chaos acceptance's correctness bar.
    Returns the mismatch count."""
    from lux_tpu.apps import components, pagerank, sssp

    bad = 0
    for r in responses:
        epoch = r.epoch or 0
        g_e = live.graph_at(epoch)
        if r.kind == "sssp":
            ref = sssp.reference_sssp_batched(
                g_e, [r.source], weighted=weighted)[:, 0]
            if not weighted:
                ref = np.where(ref >= int(sssp.HOP_INF),
                               int(sssp.HOP_INF), ref)
                ok = np.array_equal(r.answer.astype(np.int64), ref)
            else:
                ok = bool(np.allclose(r.answer, ref))
        elif r.kind == "components":
            ref = components.reference_components_batched(
                g_e, [r.source])[:, 0]
            ok = np.array_equal(r.answer.astype(np.int64), ref)
        else:
            reset = pagerank.one_hot_resets(g_e.nv, [r.source])
            ref = pagerank.reference_pagerank_batched(
                g_e, reset, max(1, r.iters))[:, 0]
            ok = bool(np.allclose(r.answer, ref, atol=5e-5))
        if not ok:
            bad += 1
            print(f"LIVE MISMATCH qid={r.qid} kind={r.kind} "
                  f"source={r.source} epoch={epoch}")
    return bad
