"""Distributed heartbeat supervision for multi-process runs.

The reference inherits liveness from the Legion/Realm runtime (a dead
GASNet node takes the whole job down, reference README.md:33-38);
lux_tpu's substrate is jax.distributed, where a lost worker process
HANGS the survivors in their next collective — there is no runtime
above the program to notice.  This module is that runtime layer, kept
deliberately boring: a shared directory of per-worker heartbeat files
(a pod's shared filesystem, or any tmp dir on the single-machine test
harness), synchronized at SEGMENT boundaries — the places the
supervised drivers (lux_tpu/resilience.py) already stop at, and the
granularity the ~55 s tunnel duration wall (PERF_NOTES round 5)
already bounds, which is what makes a wall-clock deadline a sound
death detector: a live peer can never legitimately be more than one
segment (< the deadline) behind.

Protocol (per supervised run):

- ``sync(boundary)`` at every segment boundary: write own beat (atomic
  rename), then poll the peers until every one of them has reached
  ``boundary`` (or finished).  A peer whose newest beat is older than
  ``deadline_s`` is DEAD: sync raises a typed
  :class:`WorkerLostError` — classified TOPOLOGY by
  resilience.classify — BEFORE this worker enters the next segment's
  collective, which is the difference between a diagnosed degraded
  continuation and an indefinite hang.  A peer that is merely behind
  (but beating) is a STRAGGLER: one ``straggler`` telemetry event per
  boundary, then keep waiting.
- coordinated shrink: jax.distributed cannot drop a member
  in-process, so survivors agree on the new topology through the
  board (``propose_shrink``: the LOWEST surviving pid writes the
  agreed-topology file, everyone reads the same file — deterministic
  agreement with no extra consensus machinery) and then relaunch
  degraded; the relaunched run resumes from the shared checkpoint,
  whose global ``[P, vpad, ...]`` host view re-places onto any mesh
  whose size divides num_parts (checkpoint.py, resilience.py).

Clock and sleep are injectable so the detection logic is unit-tested
with a fake clock (tests/test_elastic.py); the 2-subprocess harness
(tests/test_worker_kill.py) exercises the real thing end-to-end.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
from typing import Callable


class WorkerLostError(RuntimeError):
    """One or more peer workers missed their heartbeat deadline.
    Carries ``lost`` (worker process ids) and ``boundary``;
    resilience.classify treats it as TOPOLOGY."""

    def __init__(self, lost, boundary: int, deadline_s: float):
        lost = tuple(int(p) for p in lost)
        super().__init__(
            f"worker(s) {list(lost)} missed the heartbeat deadline "
            f"({deadline_s:g} s) at segment boundary {boundary} — "
            f"presumed dead; survivors must agree on a shrunken "
            f"topology and re-place")
        self.lost = lost
        self.boundary = int(boundary)


@dataclasses.dataclass
class ReplicaBoard:
    """Name-keyed replica heartbeat board for the serving fleet
    (lux_tpu/fleet.py, round 18) — the same shared-dir,
    atomic-rename discipline as :class:`Heartbeat`, but keyed by
    replica NAME with free-form status fields and NO boundary
    barrier: the fleet dispatcher reads beat AGES (per-replica health
    gauges, and the only death detector a hard-killed subprocess
    replica leaves behind) instead of syncing at boundaries.  A
    replica whose newest beat is older than ``deadline_s`` is
    presumed dead; the dispatcher then fails its in-flight queries
    over to the survivors."""

    path: str
    deadline_s: float = 3.0
    now: Callable[[], float] = time.time

    def __post_init__(self):
        os.makedirs(self.path, exist_ok=True)

    def _file(self, name: str) -> str:
        return os.path.join(self.path, f"rb_{name}.json")

    def beat(self, name: str, **fields) -> None:
        """Record a replica's sign of life (atomic rename: a reader
        never sees a torn beat).  Extra fields (boundary, served,
        status) ride along for the board's diagnostics."""
        fd, tmp = tempfile.mkstemp(dir=self.path, suffix=".rb.tmp")
        with os.fdopen(fd, "w") as f:
            json.dump({"replica": str(name), "t": self.now(),
                       **fields}, f)
        os.replace(tmp, self._file(name))

    def read(self, name: str) -> dict | None:
        try:
            with open(self._file(name)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def replicas(self) -> list[str]:
        """Names with a beat on the board, sorted."""
        out = []
        for f in os.listdir(self.path):
            if f.startswith("rb_") and f.endswith(".json"):
                out.append(f[3:-5])
        return sorted(out)

    def age(self, name: str) -> float | None:
        """Seconds since the replica's newest beat (None before its
        first one — the caller owns the launch grace)."""
        r = self.read(name)
        if r is None or not isinstance(r.get("t"), (int, float)):
            return None
        return max(0.0, self.now() - r["t"])

    def alive(self, name: str) -> bool:
        a = self.age(name)
        return a is not None and a <= self.deadline_s


@dataclasses.dataclass
class Heartbeat:
    """One worker's view of the shared heartbeat board.

    path        shared directory (pod filesystem / test tmp dir)
    pid         this worker's process index (0..nproc-1)
    nproc       total workers at launch
    deadline_s  staleness after which a peer is declared dead; default
                55 s = the measured tunnel duration wall, the upper
                bound on one segment's legitimate silence
    """

    path: str
    pid: int
    nproc: int
    deadline_s: float = 55.0
    poll_s: float = 0.05
    # a live-but-behind peer triggers ONE straggler event per
    # boundary once it lags this many seconds (default: half the
    # death deadline)
    straggler_s: float | None = None
    now: Callable[[], float] = time.time
    sleep: Callable[[float], None] = time.sleep
    _t_start: float = dataclasses.field(default=0.0, init=False)

    def __post_init__(self):
        os.makedirs(self.path, exist_ok=True)
        if self.straggler_s is None:
            self.straggler_s = self.deadline_s / 2
        self._t_start = self.now()

    # -- beat files ----------------------------------------------------

    def _file(self, pid: int) -> str:
        return os.path.join(self.path, f"hb_{pid}.json")

    def beat(self, boundary: int, done: bool = False) -> None:
        """Record that this worker reached ``boundary`` (atomic
        rename: a peer never reads a torn beat)."""
        fd, tmp = tempfile.mkstemp(dir=self.path, suffix=".hb.tmp")
        with os.fdopen(fd, "w") as f:
            json.dump({"pid": self.pid, "boundary": int(boundary),
                       "t": self.now(), "done": bool(done)}, f)
        os.replace(tmp, self._file(self.pid))

    def read(self, pid: int) -> dict | None:
        """A peer's newest beat, or None before its first one."""
        try:
            with open(self._file(pid)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    # -- boundary synchronization --------------------------------------

    def sync(self, boundary: int) -> None:
        """Beat, then wait for every peer to reach ``boundary`` (or
        finish).  Raises WorkerLostError when a peer's newest beat
        goes stale past ``deadline_s`` — checked HERE, before the next
        segment's collective, so a dead worker costs one deadline, not
        a hang."""
        from lux_tpu import telemetry

        t_sync = self.now()
        self.beat(boundary)
        warned = False
        while True:
            now = self.now()
            late = {}
            for p in range(self.nproc):
                if p == self.pid:
                    continue
                r = self.read(p)
                if r is not None and (r.get("done")
                                      or r.get("boundary", -1)
                                      >= boundary):
                    continue
                # age of the peer's newest sign of life (its launch is
                # its implicit first beat: a worker that never wrote
                # anything gets the same deadline from our start time)
                last = r["t"] if r is not None else self._t_start
                late[p] = now - last
            if not late:
                # one instant marker per reached boundary (round 13:
                # the tracing exporter renders these on the timeline,
                # so cross-process sync points are visible)
                telemetry.current().emit(
                    "heartbeat", boundary=int(boundary),
                    nproc=int(self.nproc),
                    waited_s=round(now - t_sync, 3))
                return
            dead = sorted(p for p, age in late.items()
                          if age > self.deadline_s)
            if dead:
                raise WorkerLostError(dead, boundary, self.deadline_s)
            if not warned and max(late.values()) > self.straggler_s:
                telemetry.current().emit(
                    "straggler", boundary=int(boundary),
                    peers=sorted(late),
                    behind_s=round(max(late.values()), 3))
                warned = True
            self.sleep(self.poll_s)

    def finish(self) -> None:
        """Mark this worker done: peers still syncing must not wait
        for boundaries a finished worker will never reach."""
        self.beat(boundary=-1, done=True)

    def survivors(self) -> list[int]:
        """Workers currently presumed alive (fresh or finished
        beats), always including self."""
        now = self.now()
        out = []
        for p in range(self.nproc):
            if p == self.pid:
                out.append(p)
                continue
            r = self.read(p)
            if r is None:
                if now - self._t_start <= self.deadline_s:
                    out.append(p)   # still within its launch grace
                continue
            if r.get("done") or now - r["t"] <= self.deadline_s:
                out.append(p)
        return out

    # -- coordinated shrink --------------------------------------------

    def _topo_file(self) -> str:
        return os.path.join(self.path, "topology.json")

    def propose_shrink(self, survivors, generation: int = 1) -> dict:
        """Agree on the degraded topology: the LOWEST surviving pid
        writes the agreed-topology file (atomic rename), every
        survivor polls until a record with this ``generation``
        appears, and all return the SAME dict — deterministic
        agreement, no consensus machinery.  The relaunch then runs
        ``len(survivors)`` processes (or one, resuming single-process)
        from the shared checkpoint."""
        from lux_tpu import telemetry

        survivors = sorted(int(p) for p in survivors)
        if self.pid == survivors[0]:
            fd, tmp = tempfile.mkstemp(dir=self.path,
                                       suffix=".topo.tmp")
            with os.fdopen(fd, "w") as f:
                json.dump({"generation": int(generation),
                           "survivors": survivors,
                           "nproc": len(survivors),
                           "t": self.now()}, f)
            os.replace(tmp, self._topo_file())
        t0 = self.now()
        while True:
            try:
                with open(self._topo_file()) as f:
                    topo = json.load(f)
            except (OSError, json.JSONDecodeError):
                topo = None
            if topo is not None and topo.get("generation") == generation:
                telemetry.current().emit(
                    "mesh_shrink", protocol="heartbeat",
                    from_nproc=int(self.nproc),
                    to_nproc=len(topo["survivors"]),
                    survivors=topo["survivors"],
                    generation=int(generation))
                return topo
            if self.now() - t0 > self.deadline_s:
                raise WorkerLostError(
                    [p for p in survivors if p != self.pid], -1,
                    self.deadline_s)
            self.sleep(self.poll_s)
