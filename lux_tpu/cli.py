"""Command-line apps with the reference's flag surface.

The reference ships one binary per app (``./pagerank -ll:gpu 4 -file
g.lux -ni 10``, reference README.md:40-52, pagerank.cc:121-148,
sssp.cc:148-180).  Here: ``python -m lux_tpu.cli <app> -file ... ``.

Flags (reference names kept):
  -file PATH    .lux graph file (required)
  -ni N         iterations (pagerank/colfilter; default 10)
  -start V      source vertex (sssp; default 0)
  -check        run the correctness audit after the run
  -verbose      per-iteration progress + phase timing
  -np N         number of partitions (the reference's -ll:gpu x nodes;
                default: the -mesh size, i.e. one partition per device)
  -mesh N       shard over an N-device mesh (default: 1 device)
  -weighted     treat the graph/run as weighted (colfilter implies it)
  -retries N    supervised run: classify + retry transient failures,
                auto-resuming from the last segment checkpoint
  -seg-budget S duration-budgeted segments (each XLA execution < S s —
                the ~55 s tunnel wall, PERF_NOTES round 5)
  -resume CKPT  checkpoint path to save to / resume from
                (all three: lux_tpu/resilience.py)
  -elastic      degraded-mesh recovery (round 11): a topology fault
                (device loss, coordination-service heartbeat loss)
                rebuilds the mesh over the surviving devices and
                resumes from the segment checkpoint instead of dying
                (supervised path + -mesh > 1 only)
  -events FILE  append structured JSONL telemetry events (header with
                graph shape + HBM estimate, per-run/segment timings,
                retries, checkpoints; lux_tpu/telemetry.py)
  -iter-stats   device-side per-iteration counters accumulated INSIDE
                the fused loop (push: frontier/edges, pull: residual/
                changed), replayed after the run — works on the fused
                AND the supervised/segmented paths
  -health       device-side health watchdog (lux_tpu/health.py):
                NaN/Inf, divergence/oscillation, frontier stalls trip
                a typed HealthError with the check/part/iteration
  -validate     structural .lux validation at load (lux_tpu/format.
                validate_graph; offline: scripts/fsck_lux.py)
  -audit MODE   static program audit at engine build (lux_tpu/audit.
                py): warn prints findings, error refuses a violating
                build with a typed AuditError (exit 2).  Repo-wide
                form: python -m lux_tpu.audit
  -calibrate    session-calibration probe before the run (lux_tpu/
                observe.py): prints/emits the fingerprint (measured
                probe ns/elem vs canonical, platform, ndev, grade) —
                a degraded tunnel session is labeled up front.
                Phase-decomposition report: python -m lux_tpu.observe

Timing methodology matches the reference: wall clock around the
iteration loop only, printed as ``ELAPSED TIME = ... s`` plus GTEPS
(reference pagerank.cc:108-118; BASELINE.md).
"""

from __future__ import annotations

import argparse
import contextlib
import sys
import time

import numpy as np


from lux_tpu.timing import fetch as _fetch
from lux_tpu.timing import timed_converge, timed_fused_run


def _min_fill_arg(v: str):
    """-min-fill value: an int, or 'auto' for the K-aware modeled
    break-even (ops/pairs.resolve_min_fill)."""
    if v == "auto":
        return "auto"
    return int(v)


def _common(ap: argparse.ArgumentParser):
    ap.add_argument("-file", required=True, help=".lux graph file")
    ap.add_argument("-np", type=int, default=0,
                    help="partitions (0 = the mesh size)")
    ap.add_argument("-mesh", type=int, default=1,
                    help="devices in the parts mesh")
    ap.add_argument("-check", action="store_true")
    ap.add_argument("-verbose", action="store_true")
    ap.add_argument("-validate", action="store_true",
                    help="validate the .lux file's structural "
                         "invariants at load (monotone row_ptrs, "
                         "col_idx in range, section sizes, degree "
                         "consistency — lux_tpu/format.validate_graph"
                         "); a malformed file exits with a typed "
                         "error instead of running to a wrong answer "
                         "(offline form: scripts/fsck_lux.py)")
    ap.add_argument("-health", action="store_true",
                    help="run under the device-side health watchdog "
                         "(lux_tpu/health.py): NaN/Inf state, "
                         "divergent/oscillating residuals and "
                         "frontier stalls accumulate an O(1) health "
                         "word inside the fused loop, checked at "
                         "run/segment boundaries; a trip raises a "
                         "typed HealthError naming the check, part "
                         "and iteration.  Compiles a separate loop "
                         "variant; the default programs are untouched")
    ap.add_argument("-audit", default=None, choices=["warn", "error"],
                    help="statically audit every compiled program "
                         "variant at engine build (lux_tpu/audit.py: "
                         "gather budget, baked-constant ceiling, "
                         "dtype discipline, collective schedule, "
                         "identity inits, no in-loop callbacks — "
                         "traced jaxprs only, nothing executes).  "
                         "'warn' prints AuditWarnings; 'error' "
                         "refuses to run a violating build (exit 2, "
                         "typed AuditError)")
    ap.add_argument("-profile", default=None, metavar="DIR",
                    help="capture an XLA profiler trace of the timed "
                         "run into DIR (view in TensorBoard/Perfetto)")
    ap.add_argument("-pair", type=int, default=None, metavar="T",
                    help="enable pair-lane delivery with threshold T "
                         "(degree-relabels the graph internally; "
                         "per-vertex results are mapped back to input "
                         "ids where printed; colfilter's edge-wise "
                         "RMSE/check need no mapping)")
    ap.add_argument("-exchange", default="auto",
                    choices=["auto", "gather", "owner"],
                    help="state exchange for pagerank/sssp/cc: "
                         "'gather' (all-gather + per-edge gather from "
                         "the full table), 'owner' (per-source-part "
                         "gathers from own shards + reduce_scatter; "
                         "2x+ once state outgrows ~64 MB — "
                         "PERF_NOTES.md), or 'auto' (owner above a "
                         "96 MB state table; the default).  "
                         "colfilter's dot path has its own dst-free "
                         "machinery and ignores this")
    ap.add_argument("-gather", default="flat",
                    choices=["flat", "paged", "pagemajor", "auto"],
                    help="state-table delivery for dense iterations: "
                         "'paged' replaces the ~9 ns/edge per-edge "
                         "gather with the page-binned row fetch + "
                         "Pallas lane shuffle (ops/pagegather.py); "
                         "'pagemajor' binds delivery rows to source "
                         "pages first (full 128-lane rows) and "
                         "routes completed rows to their destination "
                         "tiles second (owner engines: an all_to_all "
                         "routing hop); 'auto' arbitrates flat vs "
                         "paged vs page-major by the scalemodel "
                         "break-even on the plan's measured "
                         "unique-page ratio / fills (best after a "
                         "page-aware reorder, lux_tpu/reorder.py).  "
                         "Mutually exclusive with -pair (both are "
                         "row-granular delivery layouts)")
    ap.add_argument("-mxu", default="auto",
                    choices=["auto", "mxu", "vpu"],
                    help="per-chunk reduce formulation (ops/tiled."
                         "chunk_partials): 'mxu' forces the one-hot "
                         "contraction core (round 23 — sum as one "
                         "int8 matmul, min/max as the bit-serial "
                         "tournament, the segmented combine as "
                         "blocked scan-as-matmul), 'vpu' forces the "
                         "fused masked broadcast-reduce, 'auto' "
                         "(default) engages the MXU when the "
                         "program's K x B payload width amortizes "
                         "the one-hot toll (scalemodel."
                         "mxu_break_even_wide: wide >= 2 for sum — "
                         "batched/K-dim programs — never for "
                         "min/max)")
    ap.add_argument("-min-fill", type=_min_fill_arg, default=None,
                    dest="min_fill", metavar="F",
                    help="with -pair: drop pair rows that would "
                         "deliver < F live lanes (their edges ride "
                         "the residual path); break-even ~15 at the "
                         "measured 150 ns/row vs ~10 ns/edge rates "
                         "(PERF_NOTES round 5).  'auto' picks the "
                         "K-AWARE modeled break-even (~16 scalar, "
                         "~22 for colfilter's K=20 SDDMM rows — "
                         "scalemodel.break_even_fill)")
    ap.add_argument("-sparse", type=int, default=1, metavar="0|1",
                    help="sssp/cc: keep the src-sorted sparse-frontier "
                         "view (1, default).  0 halves edge memory at "
                         "big scale; every iteration runs dense "
                         "(memory_report(push_sparse=...) prices it)")
    ap.add_argument("-retries", type=int, default=0, metavar="N",
                    help="supervise the run (lux_tpu.resilience): "
                         "classify failures, retry transient ones up "
                         "to N times with exponential backoff, and "
                         "auto-resume from the last segment "
                         "checkpoint instead of restarting")
    ap.add_argument("-seg-budget", type=float, default=0.0,
                    dest="seg_budget", metavar="S",
                    help="run in duration-budgeted segments: size "
                         "each XLA execution to stay under S seconds "
                         "(the ~55 s tunnel duration wall, PERF_NOTES "
                         "round 5); implies the supervised path")
    ap.add_argument("-elastic", action="store_true",
                    help="with the supervised path (-retries/"
                         "-seg-budget/-resume) and -mesh > 1: survive "
                         "device loss.  A TOPOLOGY-classified failure "
                         "(device unavailable, coordination-service "
                         "heartbeat loss) rebuilds the mesh over the "
                         "surviving devices — the largest count "
                         "dividing -np — re-places the checkpointed "
                         "state, and resumes degraded instead of "
                         "dying (lux_tpu/resilience.py round 11)")
    ap.add_argument("-resume", default=None, metavar="CKPT",
                    help="checkpoint file: save after every segment "
                         "and resume from it if it exists; implies "
                         "the supervised path (without -resume, "
                         "-retries/-seg-budget checkpoint to a "
                         "temporary file for in-run crash recovery "
                         "only).  Supervised timing includes segment "
                         "checkpoint saves")
    ap.add_argument("-events", default=None, metavar="FILE",
                    help="append structured telemetry events to FILE "
                         "as JSONL (one object per line; schema in "
                         "lux_tpu/telemetry.py, rendered by "
                         "scripts/events_summary.py): graph header "
                         "with the HBM estimate, timed-run/segment "
                         "seconds, classified retries, checkpoint "
                         "saves/resumes")
    ap.add_argument("-iter-stats", action="store_true",
                    dest="iter_stats",
                    help="record device-side per-iteration counters "
                         "inside the fused loop (push: frontier size "
                         "+ edges relaxed; pull: residual + changed "
                         "vertices) and replay them after the run — "
                         "unlike the old stepwise -verbose this "
                         "neither changes the timed path's shape nor "
                         "adds host syncs, and it composes with "
                         "-retries/-seg-budget segment runs")
    ap.add_argument("-phases", type=int, default=0, metavar="N",
                    help="after the timed run, run N instrumented "
                         "iterations and print the per-iteration "
                         "phase split (gather/reduce/exchange/apply; "
                         "separate fenced programs — read relative "
                         "weights, not GTEPS; iter 0 includes "
                         "compilation)")
    ap.add_argument("-flight", default=None, metavar="FILE",
                    help="install the crash flight recorder "
                         "(lux_tpu/tracing.py): a bounded ring of "
                         "recent telemetry events plus the last "
                         "health word and placement metadata, dumped "
                         "atomically to FILE by the resilience "
                         "supervisor on fatal failures and topology "
                         "faults — a dead run through the tunnel "
                         "stays diagnosable after the fact (render: "
                         "scripts/events_summary.py -flight FILE)")
    ap.add_argument("-sources", default=None, metavar="A,B,C",
                    help="comma list of query sources: runs the "
                         "QUERY-BATCHED engine (ROADMAP item 2) — "
                         "k-source SSSP / seeded components / "
                         "personalized (one-hot reset) pagerank — "
                         "with one state column per query, ONE "
                         "gather serving all of them.  Composes "
                         "with -retries/-seg-budget/-iter-stats/"
                         "-health; -pair and sssp -delta are "
                         "single-query machinery and must be off")
    ap.add_argument("-batch", type=int, default=0, metavar="B",
                    help="without -sources: build a B-query batch "
                         "from evenly spaced source vertices; with "
                         "-sources: must match the list length "
                         "(sanity check).  The serving front-end is "
                         "python -m lux_tpu.serve")
    ap.add_argument("-calibrate", action="store_true",
                    help="run the session-calibration probe "
                         "(lux_tpu/observe.py) before the run and "
                         "print/emit the fingerprint — labels this "
                         "process's measured primitive rate vs the "
                         "canonical PERF_NOTES figures, so a "
                         "degraded tunnel session is detected before "
                         "any number is read")


def _load(args, weighted: bool):
    from lux_tpu.format import GraphFormatError
    from lux_tpu.graph import Graph

    import os
    if not os.path.exists(args.file):
        print(f"error: graph file not found: {args.file}", file=sys.stderr)
        raise SystemExit(2)
    t0 = time.perf_counter()
    try:
        g = Graph.from_file(args.file, weighted=weighted or None,
                            validate=getattr(args, "validate", False))
    except GraphFormatError as e:
        # a malformed graph is a typed, named refusal — never a run
        # that silently computes wrong answers through clamping gathers
        print(f"error: {e}", file=sys.stderr)
        raise SystemExit(2)
    if args.verbose:
        print(f"loaded nv={g.nv} ne={g.ne} weighted={g.weights is not None}"
              f" ({time.perf_counter() - t0:.2f}s)")
    return g


def _mesh_and_parts(args):
    from lux_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(args.mesh) if args.mesh > 1 else None
    num_parts = args.np or (args.mesh if args.mesh > 1 else 1)
    if mesh is not None and num_parts % args.mesh:
        rounded = args.mesh * ((num_parts + args.mesh - 1) // args.mesh)
        print(f"note: -np {num_parts} rounded up to {rounded} "
              f"(must divide the {args.mesh}-device mesh)")
        num_parts = rounded
    return mesh, num_parts


def _print_phases(report, tel=None):
    """Per-iteration phase table — the analogue of the reference's
    -verbose per-iteration loadTime/compTime/updateTime prints
    (reference sssp_gpu.cu:513-518).  With a telemetry handle the
    table also lands in the event log as one ``phases`` event, which
    scripts/events_summary.py renders back into the reference-style
    table."""
    META = ("frontier", "bucket", "advances")   # counters, not times
    for i, t in enumerate(report):
        extra = "".join(f" {k}={t[k]:g}" for k in META if k in t)
        split = "  ".join(f"{k}={v * 1e3:7.2f}ms" for k, v in t.items()
                          if k not in META)
        print(f"iter {i}:{extra}  {split}")
    if tel is not None:
        tel.emit("phases", iters=len(report),
                 report=[{k: (v if k in META else round(v, 6))
                          for k, v in t.items()} for t in report])


def _batched_sources(args, nv: int):
    """None, or the resolved query-source list from -sources/-batch
    (ROADMAP item 2 batched engines).  -batch without -sources draws
    B evenly spaced vertices — deterministic, so batched CLI runs
    are reproducible."""
    srcs = getattr(args, "sources", None)
    B = int(getattr(args, "batch", 0) or 0)
    if srcs is None and not B:
        return None
    if getattr(args, "pair", None) is not None:
        print("error: -pair is single-query machinery (pair delivery "
              "reads scalar state); drop it for -sources/-batch runs",
              file=sys.stderr)
        raise SystemExit(2)
    if srcs is not None:
        try:
            out = [int(s) for s in srcs.split(",") if s.strip()]
        except ValueError:
            print(f"error: -sources must be a comma list of vertex "
                  f"ids, got {srcs!r}", file=sys.stderr)
            raise SystemExit(2)
        if not out:
            print("error: -sources named no vertices", file=sys.stderr)
            raise SystemExit(2)
        if B and B != len(out):
            print(f"error: -batch {B} != len(-sources) = {len(out)}",
                  file=sys.stderr)
            raise SystemExit(2)
    else:
        out = [int(x) for x in
               np.linspace(0, nv - 1, B).round().astype(np.int64)]
    for s in out:
        if not 0 <= s < nv:
            print(f"error: source vertex {s} out of range [0, {nv})",
                  file=sys.stderr)
            raise SystemExit(2)
    return out


def _print_batch(sources, ne, iters, elapsed):
    """The batched runs' per-query delivered-rate line (the metric
    bench.py's batch-sweep records as query_gteps)."""
    B = len(sources)
    if iters > 0 and elapsed > 0:
        qg = ne * iters * B / elapsed / 1e9
        print(f"BATCH = {B} queries; QUERY-GTEPS = {qg:.4f} "
              f"({1.0 / qg:.1f} ns/edge/query delivered)")
    else:
        print(f"BATCH = {B} queries")


def _maybe_calibrate(args):
    """-calibrate: run (or reuse) the session probe and print the
    fingerprint header; inside a telemetry scope the ``calibration``
    event lands in the log too (observe.calibrate emits it)."""
    if not getattr(args, "calibrate", False):
        return
    from lux_tpu import observe
    fp = observe.calibrate()
    print(f"# calibration: session {fp.session} {fp.platform}/"
          f"{fp.backend} ndev={fp.ndev} grade={fp.grade} — gather "
          f"{fp.probe['gather_small_ns']:.2f} ns/elem "
          f"({fp.deviation:.2f}x canonical)")
    if fp.grade == "degraded":
        print("# WARNING: DEGRADED session (PERF_NOTES tunnel "
              "variance) — numbers from this process are labeled, "
              "not trusted")


@contextlib.contextmanager
def _telemetry(args, app):
    """Scope the run's telemetry sinks (lux_tpu/telemetry.py) from
    -events / -iter-stats.  Without either flag this is the null
    handle and every emit stays a no-op; engines keep building their
    counter-free programs."""
    from lux_tpu import telemetry

    if getattr(args, "flight", None):
        from lux_tpu import tracing
        tracing.install_flight_recorder(args.flight)
    if not (args.events or args.iter_stats):
        _maybe_calibrate(args)
        yield telemetry.current()
        return
    ev = telemetry.EventLog(args.events) if args.events else None
    st = telemetry.IterStats() if args.iter_stats else None
    try:
        with telemetry.use(events=ev, iter_stats=st) as tel:
            tel.emit("run_start", schema=telemetry.SCHEMA, app=app,
                     file=args.file, mesh=args.mesh,
                     np=args.np or None)
            _maybe_calibrate(args)
            yield tel
    finally:
        if ev is not None:
            ev.close()


def _finish_run(tel, elapsed, iters):
    """Close out one timed run: emit the ``run_done`` event
    (scripts/events_summary.py checks segment seconds against it) and
    replay the device-side per-iteration counters when -iter-stats
    recorded them — the exact series the old stepwise -verbose path
    printed, now read from the fused run's buffers."""
    tel.emit("run_done", seconds=round(elapsed, 6), iters=iters)
    st = tel.iter_stats
    if st is None or st.kind is None:
        return
    print("# iter-stats (device-side counters, fused run):")
    for line in st.replay_lines():
        print(line)
    # per-part imbalance attribution (round 13): the measured skew
    # signal the locality-aware partitioner will optimize
    for line in st.parts_lines():
        print(f"# {line}")
    # the digest's "kind" (push|pull) would shadow the event kind
    tel.emit("iter_stats", **{("engine" if k == "kind" else k): v
                              for k, v in st.summary().items()})


def _mxu_arg(args):
    """-mxu auto|mxu|vpu -> the engines' use_mxu value."""
    m = getattr(args, "mxu", "auto")
    return {"auto": "auto", "mxu": True, "vpu": False}[m]


def _warn_exchange_ignored(args):
    """colfilter's dot path has its own dst-free delivery; -exchange
    does not apply there."""
    if args.exchange not in ("gather", "auto"):
        print(f"note: -exchange {args.exchange} does not apply to "
              f"colfilter's dot path; ignored")


def _supervisor_opts(args, app):
    """None, or (checkpoint path, supervised-run kwargs) when any of
    -retries / -seg-budget / -resume asks for the resilience
    supervisor (lux_tpu/resilience.py)."""
    if not (args.retries > 0 or args.seg_budget > 0 or args.resume):
        if getattr(args, "elastic", False):
            # never drop a recovery flag silently: without the
            # supervised path there is no checkpoint to re-place from
            print("note: -elastic implies the supervised path; add "
                  "-retries/-seg-budget/-resume or it cannot recover "
                  "anything; ignored")
        return None
    import os
    import tempfile

    from lux_tpu import resilience

    if getattr(args, "profile", None):
        print("note: -profile is ignored on the supervised path "
              "(segments are separate XLA executions)")
    if getattr(args, "verbose", False):
        print("note: -verbose is ignored on the supervised path; "
              "-iter-stats records per-iteration counters across "
              "segments instead")
    # pid-qualified: concurrent runs must not clobber (or worse,
    # cross-resume) each other's in-run recovery checkpoints
    path = args.resume or os.path.join(
        tempfile.gettempdir(),
        f"lux_{app}_supervised.{os.getpid()}.ckpt.npz")
    kw = dict(policy=resilience.RetryPolicy(retries=max(0, args.retries)),
              seg_budget=args.seg_budget or None,
              resume=args.resume is not None)
    return path, kw


def _run_supervised(eng, sup, args, ni=None, make_engine=None):
    """One supervised execution (pull fixed-``ni``, or push converge
    when ni is None), printing the supervisor report and reclaiming
    the implicit (non -resume) recovery checkpoint on BOTH success
    and failure — its pid-qualified name means nothing else ever
    would.  Returns (result, total_iters, elapsed, billed, mark):
    ``billed`` excludes iterations a previous invocation's -resume
    checkpoint already did (in-run retries bill in full — redone
    segments and backoff are this run's cost, resilience.RunReport
    .initial_resume).

    make_engine(mesh) — the app's engine factory — plus -elastic arms
    degraded-mesh recovery: a topology fault rebuilds over the
    survivors and resumes instead of dying."""
    import os

    from lux_tpu import resilience

    path, kw = sup
    if getattr(args, "elastic", False):
        if make_engine is not None and args.mesh > 1:
            kw = dict(kw, elastic=make_engine)
            if kw["policy"].retries < 1:
                # the topology handler only runs with retry budget
                # left (supervise: k < retries) — armed-but-inert
                # must not be silent
                print("note: -elastic needs -retries >= 1 to re-place "
                      "after a topology fault; a fault will be fatal")
        else:
            print("note: -elastic needs -mesh > 1 (a single device "
                  "has no topology to shrink); ignored")
    t0 = time.perf_counter()
    try:
        if ni is not None:
            result, report = resilience.supervised_run(eng, ni, path,
                                                       **kw)
            total = ni
        else:
            label, _active, total, report = \
                resilience.supervised_converge(eng, path, **kw)
            result = eng.unpad(label)
        elapsed = time.perf_counter() - t0
    finally:
        if not args.resume:
            from lux_tpu import checkpoint
            checkpoint.remove(path)     # both generations
    print(f"# supervisor: attempts={report.attempts} "
          f"segments={report.segments} "
          f"resumed_from={report.resumed_from}")
    if report.topology:
        hops = " -> ".join(
            [str(report.topology[0]['from_ndev'])]
            + [str(t['to_ndev']) for t in report.topology])
        print(f"# supervisor: DEGRADED — mesh shrank {hops} devices "
              f"(lost {[t['lost_devices'] for t in report.topology]}); "
              f"results are exact, timings are not comparable to "
              f"full-mesh runs")
    billed = total - (report.initial_resume or 0)
    return (result, total, elapsed, billed,
            " (supervised; incl. checkpoint saves)")


def _relabel_for_pairs(args, g, num_parts):
    """-pair T: relabel so pair-lane delivery finds dense tile pairs
    (degree sort + tile round-robin over parts).  Returns (graph to
    run on, perm|None, starts|None) with perm[new]=old."""
    if getattr(args, "pair", None) is None:
        return g, None, None
    from lux_tpu.graph import pair_relabel
    g2, perm, starts = pair_relabel(g, num_parts,
                                    pair_threshold=args.pair)
    if args.verbose:
        print(f"pair-lane: degree relabel + threshold {args.pair}")
    return g2, perm, starts


def _build_sg(args, g, num_parts, starts=None):
    """Build the padded layout once; print the memory advisor (the
    analogue of the reference's startup requirement estimate,
    reference pagerank.cc:60-85) under -verbose."""
    from lux_tpu.graph import ShardedGraph

    # -gather paged|auto: the paged plan needs 128-aligned vertex
    # padding, like pair delivery (ops/pagegather.py)
    paged = getattr(args, "gather", "flat") != "flat"
    sg = ShardedGraph.build(g, num_parts, starts=starts,
                            pair_threshold=getattr(args, "pair", None),
                            vpad_align=128 if paged else 8)
    from lux_tpu import telemetry
    telemetry.current().emit("header", schema=telemetry.SCHEMA,
                             **sg.telemetry_header())
    if args.verbose:
        rep = sg.memory_report()
        print(f"memory: {rep['total_bytes'] / 1e6:.1f} MB total over "
              f"{num_parts} part(s) "
              f"({rep['edge_bytes_per_part'] / 1e6:.1f} MB edges + "
              f"{rep['vertex_bytes_per_part'] / 1e6:.1f} MB vertices "
              f"per part)")
    return sg


def cmd_pagerank(argv):
    ap = argparse.ArgumentParser(prog="lux_tpu pagerank")
    _common(ap)
    ap.add_argument("-ni", type=int, default=10)
    ap.add_argument("-tol", type=float, default=None,
                    help="run to convergence (max-abs change of the "
                         "degree-scaled rank state <= tol) instead of "
                         "a fixed -ni count")
    ap.add_argument("-max-iters", type=int, default=10000,
                    dest="max_iters",
                    help="iteration cap for -tol runs (default 10000)")
    args = ap.parse_args(argv)

    from lux_tpu.apps import pagerank

    with _telemetry(args, "pagerank") as tel:
        g = _load(args, weighted=False)
        mesh, num_parts = _mesh_and_parts(args)
        sources = _batched_sources(args, g.nv)
        g_run, perm, starts = _relabel_for_pairs(args, g, num_parts)
        sg = _build_sg(args, g_run, num_parts, starts)
        def make_eng(m):
            # the -elastic factory: same graph/config, new mesh —
            # engines compile per-mesh automatically (arrays are jit
            # arguments), and the rebuilt engine re-audits under the
            # same -audit mode at the new device count.  -sources
            # builds the personalized (one-hot reset) batched engine
            # (ROADMAP item 2).
            return pagerank.build_engine(g_run, num_parts, m, sg=sg,
                                         pair_threshold=args.pair,
                                         pair_min_fill=args.min_fill,
                                         exchange=args.exchange,
                                         gather=args.gather,
                                         use_mxu=_mxu_arg(args),
                                         health=args.health,
                                         sources=sources,
                                         audit=args.audit)

        eng = make_eng(mesh)
        if args.tol is not None:
            if args.retries > 0 or args.seg_budget > 0 or args.resume:
                print("note: -tol runs one monolithic convergence "
                      "program; -retries/-seg-budget/-resume apply to "
                      "fixed -ni runs only and are ignored here")
            from lux_tpu.timing import timed_run_until
            state, iters, res, elapsed = timed_run_until(
                eng, args.tol, args.max_iters, trace_dir=args.profile)
            print(f"ELAPSED TIME = {elapsed:.7f} s ({iters} iterations, "
                  f"residual {res:.3e})")
            print(f"GTEPS = {g.ne * iters / elapsed / 1e9:.4f}")
            if sources is not None:
                _print_batch(sources, g.ne, iters, elapsed)
            _finish_run(tel, elapsed, iters)
        else:
            sup = _supervisor_opts(args, "pagerank")
            if sup is not None:
                state, total, elapsed, ni, mark = _run_supervised(
                    eng, sup, args, ni=args.ni, make_engine=make_eng)
            else:
                state, [elapsed] = timed_fused_run(
                    eng, args.ni, trace_dir=args.profile)
                total = ni = args.ni
                mark = ""
            print(f"ELAPSED TIME = {elapsed:.7f} s")
            if ni > 0:
                print(f"GTEPS = {g.ne * ni / elapsed / 1e9:.4f}{mark}")
            else:
                print("GTEPS = n/a (run already complete in checkpoint)")
            if sources is not None:
                _print_batch(sources, g.ne, ni, elapsed)
            _finish_run(tel, elapsed, total)

        if args.phases:
            _state, rep = eng.timed_phases(eng.init_state(), args.phases)
            _print_phases(rep, tel)
        if sources is not None and args.check:
            # per-column device_check rides the batch-sweep debt
            print("note: -check does not support batched runs yet; "
                  "skipped (oracle proofs: tests/test_batched.py)")
            return 0
        if args.check:
            # On-device sharded audit over the resident edge arrays
            # (the reference's per-part GPU check tasks,
            # sssp_gpu.cu:800-843); runs at any scale, no host
            # edge-list rebuild.  NOTE: audits the FULL sg built
            # above, not eng.sg (pair-lane engines keep only the
            # residual edges there).  The residual is
            # permutation-invariant, so no -pair un-relabel is needed.
            from lux_tpu.device_check import check_pagerank_device
            res = check_pagerank_device(sg, state, tol=1e-3,
                                        mesh=eng.mesh)
            print(res)
            return 0 if res.ok else 1
    return 0


def _push_app(argv, prog_name):
    ap = argparse.ArgumentParser(prog=f"lux_tpu {prog_name}")
    _common(ap)
    ap.add_argument("-start", type=int, default=0)
    ap.add_argument("-weighted", action="store_true")
    if prog_name == "sssp":
        ap.add_argument("-delta", default=None,
                        help="delta-stepping bucket width (a number or "
                             "'auto'; default: off)")
    args = ap.parse_args(argv)

    from lux_tpu.apps import components, sssp

    weighted = prog_name == "sssp" and args.weighted
    with _telemetry(args, prog_name) as tel:
        g = _load(args, weighted=weighted)
        mesh, num_parts = _mesh_and_parts(args)
        sources = _batched_sources(args, g.nv)
        g_run, perm, starts = _relabel_for_pairs(args, g, num_parts)
        sg = _build_sg(args, g_run, num_parts, starts)
        start = args.start if prog_name == "sssp" else None
        if perm is not None and start is not None:
            rank = np.empty(g.nv, np.int64)
            rank[perm] = np.arange(g.nv)
            start = int(rank[start])
        if prog_name == "sssp":
            delta = args.delta
            if delta is not None and delta != "auto":
                delta = float(delta)
            if sources is not None and delta is not None:
                print("error: -delta is single-query machinery; drop "
                      "it for -sources/-batch runs", file=sys.stderr)
                return 2

            def make_eng(m):
                return sssp.build_engine(
                    g_run, start_vertex=start, num_parts=num_parts,
                    mesh=m, weighted=weighted, delta=delta, sg=sg,
                    pair_threshold=args.pair,
                    pair_min_fill=args.min_fill,
                    exchange=args.exchange,
                    gather=args.gather,
                    enable_sparse=bool(args.sparse),
                    use_mxu=_mxu_arg(args),
                    sources=sources,
                    health=args.health, audit=args.audit)
        else:
            def make_eng(m):
                return components.build_engine(
                    g_run, num_parts=num_parts, mesh=m, sg=sg,
                    pair_threshold=args.pair,
                    pair_min_fill=args.min_fill,
                    exchange=args.exchange,
                    gather=args.gather,
                    enable_sparse=bool(args.sparse),
                    use_mxu=_mxu_arg(args),
                    sources=sources,
                    health=args.health, audit=args.audit)
        eng = make_eng(mesh)
        sup = _supervisor_opts(args, prog_name)
        if sup is not None:
            labels, iters, elapsed, it_exec, mark = _run_supervised(
                eng, sup, args, make_engine=make_eng)
        else:
            labels, iters, [elapsed] = timed_converge(
                eng, verbose=args.verbose, trace_dir=args.profile)
            it_exec, mark = iters, ""
        print(f"ELAPSED TIME = {elapsed:.7f} s ({iters} iterations)")
        if it_exec > 0:
            print(f"GTEPS = {g.ne * it_exec / elapsed / 1e9:.4f}{mark}")
        else:
            print("GTEPS = n/a (run already complete in checkpoint)")
        if sources is not None:
            _print_batch(sources, g.ne, it_exec, elapsed)
        _finish_run(tel, elapsed, iters)

        if args.phases:
            lab0, act0 = eng.init_state()
            _l, _a, rep = eng.timed_phases(lab0, act0, args.phases)
            _print_phases(rep, tel)
        if sources is not None and args.check:
            # per-column device_check needs the batched fixed-point
            # audits (carried with the on-device batch sweep debt,
            # lux_tpu/observe.py); the oracle proofs live in
            # tests/test_batched.py
            print("note: -check does not support batched runs yet; "
                  "skipped")
            return 0
        if args.check:
            # On-device per-part audits (reference sssp_gpu.cu:800-843,
            # components_gpu.cu:788); labels are in g_run order, which
            # is exactly sg's order — the fixed-point properties are
            # permutation-invariant, so no -pair un-relabel is needed.
            from lux_tpu import device_check
            if prog_name == "sssp":
                res = device_check.check_sssp_device(
                    sg, labels, weighted=weighted, mesh=eng.mesh)
            else:
                res = device_check.check_components_device(
                    sg, labels, mesh=eng.mesh)
            print(res)
            return 0 if res.ok else 1
    return 0


def cmd_sssp(argv):
    return _push_app(argv, "sssp")


def cmd_components(argv):
    return _push_app(argv, "components")


def cmd_colfilter(argv):
    ap = argparse.ArgumentParser(prog="lux_tpu colfilter")
    _common(ap)
    ap.add_argument("-ni", type=int, default=10)
    args = ap.parse_args(argv)

    from lux_tpu.apps import colfilter

    _warn_exchange_ignored(args)
    if getattr(args, "sources", None) or getattr(args, "batch", 0):
        print("note: colfilter trains one shared factorization; "
              "-sources/-batch apply to sssp/components/pagerank "
              "(per-user top-N serving is future work); ignored")
    with _telemetry(args, "colfilter") as tel:
        g = _load(args, weighted=True)
        mesh, num_parts = _mesh_and_parts(args)
        g_run, _perm, starts = _relabel_for_pairs(args, g, num_parts)
        sg = _build_sg(args, g_run, num_parts, starts)
        def make_eng(m):
            return colfilter.build_engine(g_run, num_parts, m, sg=sg,
                                          pair_threshold=args.pair,
                                          pair_min_fill=args.min_fill,
                                          gather=args.gather,
                                          use_mxu=_mxu_arg(args),
                                          health=args.health,
                                          audit=args.audit)

        eng = make_eng(mesh)
        sup = _supervisor_opts(args, "colfilter")
        if sup is not None:
            state, total, elapsed, ni, mark = _run_supervised(
                eng, sup, args, ni=args.ni, make_engine=make_eng)
        else:
            state, [elapsed] = timed_fused_run(eng, args.ni,
                                               trace_dir=args.profile)
            total = ni = args.ni
            mark = ""
        print(f"ELAPSED TIME = {elapsed:.7f} s")
        if ni > 0:
            print(f"GTEPS = {g.ne * ni / elapsed / 1e9:.4f}{mark}")
        else:
            print("GTEPS = n/a (run already complete in checkpoint)")
        _finish_run(tel, elapsed, total)
        out = eng.unpad(state)
        # out is in the run graph's (possibly relabeled) vertex order;
        # rmse is computed over edges, so the relabeled graph is the
        # matching — and equivalent — choice
        print(f"RMSE = {colfilter.rmse(g_run, out):.6f}")
        if args.phases:
            _state, rep = eng.timed_phases(eng.init_state(), args.phases)
            _print_phases(rep, tel)
        if args.check:
            from lux_tpu.device_check import check_colfilter_device
            res = check_colfilter_device(sg, out, mesh=eng.mesh)
            print(res)
            return 0 if res.ok else 1
    return 0


def cmd_convert(argv):
    ap = argparse.ArgumentParser(prog="lux_tpu convert")
    ap.add_argument("-input", required=True, help="text edge list")
    ap.add_argument("-output", required=True, help=".lux output")
    ap.add_argument("-nv", type=int, required=True)
    ap.add_argument("-weighted", action="store_true")
    args = ap.parse_args(argv)

    from lux_tpu.convert import convert_edge_list
    convert_edge_list(args.input, args.output, args.nv,
                      weighted=args.weighted)
    return 0


_APPS = {
    "pagerank": cmd_pagerank,
    "sssp": cmd_sssp,
    "components": cmd_components,
    "colfilter": cmd_colfilter,
    "convert": cmd_convert,
}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m lux_tpu.cli "
              f"{{{','.join(_APPS)}}} [flags]\n"
              "run 'python -m lux_tpu.cli <app> -h' for app flags")
        return 0 if argv else 2
    app = argv[0]
    if app not in _APPS:
        print(f"unknown app {app!r}; choose from {list(_APPS)}",
              file=sys.stderr)
        return 2
    try:
        return _APPS[app](argv[1:])
    except Exception as e:
        from lux_tpu.audit import AuditError
        if isinstance(e, AuditError):
            # -audit error: a violating build is a typed, named
            # refusal (like GraphFormatError), never a run whose
            # numbers silently embed the violation
            print(f"error: {e}", file=sys.stderr)
            return 2
        raise


if __name__ == "__main__":
    sys.exit(main())
