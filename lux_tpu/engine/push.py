"""The push engine: frontier-driven label propagation to convergence.

The reference's push model (reference core/push_model.inl,
sssp_gpu.cu:335-522) keeps per-partition frontier queues with
dense-bitmap/sparse-queue representations, exchanges them through
zero-copy memory each iteration, pipelines SLIDING_WINDOW=4 launches,
and halts when every part's future reports an empty frontier
(sssp.cc:115-129).

The TPU-native design:

- The CANONICAL frontier is a dense boolean mask in the padded
  part-major vertex layout — a shape-stable array that all-gathers
  trivially over ICI (SURVEY.md §7 "sparse frontiers" hard part).
- Each iteration picks one of two execution strategies with a real
  ``lax.cond`` branch (the analogue of the reference's adaptive
  pull/push switch on ``frontier > nv/16``, sssp_gpu.cu:414-421):
  * DENSE: masked pull over every edge — inactive sources contribute
    the reduction identity (tiled scatter-free segment reduction).
  * SPARSE: compact the mask into capacity-bounded padded queues of
    (vertex, label) pairs, exchange the queues (all-gather over ICI —
    O(queue) bytes, not O(nv)), and relax only the frontier's
    out-edges through the src-sorted CSR view (engine/frontier.py).
  The cond predicate is replicated (a psum), so the branch stays a
  branch — it is deliberately hoisted OUTSIDE the per-part vmap,
  where it would decay into select-both-sides.
- Sparse overflow safety: when a frontier's out-edges exceed the
  static edge budget, the un-expanded queue suffix simply STAYS
  ACTIVE (the globally-agreed processed prefix is cleared via a
  pmin), so truncation degrades performance, never correctness —
  the reference instead re-densifies on queue overflow
  (sssp_gpu.cu:485-490).
- The ENTIRE convergence run is one XLA program: ``lax.while_loop``
  whose predicate is a ``psum`` of active counts.  There is no
  device->host sync per iteration at all, so the reference's
  SLIDING_WINDOW=4 latency-hiding trick is unnecessary by
  construction.
"""

from __future__ import annotations

import dataclasses
import functools

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from lux_tpu.engine import frontier as fr
from lux_tpu.engine.auditable import AuditableEngine
from lux_tpu.engine.program import vmask_of
from lux_tpu.graph import ShardedGraph
from lux_tpu.ops.segment import segment_reduce
from lux_tpu.ops.tiled import tiled_segment_reduce
from lux_tpu.parallel.mesh import PARTS_AXIS, shard_over_parts
from lux_tpu.partition import frontier_capacity


@dataclasses.dataclass(frozen=True)
class PushProgram:
    """Monotone label-propagation program.

    reduce    'min' (SSSP/BFS) or 'max' (components) — the atomicMin/
              atomicMax of the reference's process_edge (sssp_gpu.cu:
              48-82, components_gpu.cu:57-59).
    relax     (src_label [epad], weight [epad]|None) -> candidate label
              offered to the edge's destination.
    identity  scalar no-op candidate (+inf for min, -inf/0 for max).
    init      (sharded_graph) -> (label0 [num_parts, vpad],
              active0 bool [num_parts, vpad]) numpy.
    name      optional app label; engines scope their traced step in
              ``jax.named_scope(f"lux_{name}")`` so profiler captures
              (profiling.trace) attribute device ops to the app.
    batch     query-batch width B when labels/active carry a trailing
              query axis ``[vpad, B]`` (None = single-query).  Each
              column is one independent query: its active mask is its
              frontier, a retired (converged) column is all-inactive
              and contributes the reduce identity through the same
              pre-gather mask as any inactive source — ONE label
              gather serves all B queries (audit gather-budget).
              Batched engines run every iteration DENSE (per-query
              sparse queues are not implemented) and reject
              delta-stepping and pair-lane delivery.
    """
    reduce: str
    relax: Callable
    identity: Any
    init: Callable
    name: str | None = None
    batch: int | None = None

    def better(self, cand, old):
        return cand < old if self.reduce == "min" else cand > old


class PushEngine(AuditableEngine):
    """Compiled frontier iterations for one ShardedGraph + PushProgram."""

    def __init__(self, sg: ShardedGraph, program: PushProgram, mesh=None,
                 layout: str = "tiled", tile_w: int = 128,
                 tile_e: int = 512, use_mxu: bool | str = "auto",
                 enable_sparse: bool = True,
                 sparse_threshold: int = 16,
                 edge_budget: int | None = None,
                 delta: float | None = None,
                 reduce_method: str = "auto",
                 pair_threshold: int | None = None,
                 pair_min_fill: int | str | None = None,
                 pair_stream: bool | None = None,
                 stream_msgs: bool | None = None,
                 exchange: str = "auto",
                 gather: str = "flat",
                 owner_tile_e: int | None = None,
                 owner_minmax_fused: bool = False,
                 stats_cap: int | None = None,
                 health: bool = False,
                 audit: str | None = None):
        if mesh is not None and sg.num_parts % mesh.devices.size != 0:
            raise ValueError(
                f"num_parts={sg.num_parts} not divisible by mesh size "
                f"{mesh.devices.size}")
        from lux_tpu.engine.pull import (_check_local_parts,
                                         build_graph_arrays,
                                         resolve_exchange,
                                         resolve_reduce_method,
                                         resolve_use_mxu)
        _check_local_parts(sg, mesh, pair_threshold)
        # query-batched labels [vpad, B] (program.batch = B): dense
        # masked iterations only — columns retire independently
        # through their own active masks; sparse queues, delta
        # buckets and pair rows are single-query machinery
        self.batch = getattr(program, "batch", None)
        if self.batch is not None:
            if delta is not None:
                raise ValueError(
                    "delta-stepping is single-query (one scalar "
                    "bucket bound); build batched engines with "
                    "delta=None")
            if pair_threshold is not None:
                raise ValueError(
                    "pair_threshold does not support query-batched "
                    "programs: pair delivery reads scalar vertex "
                    "state (ops/pairs.pair_partial)")
            enable_sparse = False
        # the auto-exchange table estimate is in BYTES of the whole
        # label table — a B-wide batch is B tables
        ident_dt = np.asarray(program.identity).dtype
        exchange = resolve_exchange(
            exchange, sg, program,
            itemsize=ident_dt.itemsize * (self.batch or 1))
        self.exchange = exchange
        # fused (ring reduce-scatter) min/max owner exchange — opt-in,
        # see ops/owner.owner_exchange
        self.owner_minmax_fused = bool(owner_minmax_fused)
        if delta is not None:
            if program.reduce != "min":
                raise ValueError("delta-stepping requires a 'min' program")
            # validate in the LABEL dtype: a fractional delta truncates
            # to 0 on int32 hop labels and would spin the bucket loop
            # forever without progress
            ldt = np.asarray(program.identity).dtype
            if not float(np.asarray(delta, ldt)) > 0:
                raise ValueError(
                    f"delta-stepping bucket width {delta!r} is not > 0 "
                    f"in label dtype {ldt}")
        self.sg = sg
        self.program = program
        self.mesh = mesh
        self.delta = delta
        # health=True: run()/segmented drivers use the watchdog loop
        # variant (converge_health, compiled lazily); False leaves
        # every watchdog-free program untouched
        self.health = bool(health)
        from lux_tpu.telemetry import DEFAULT_STATS_CAP
        self.stats_cap = int(stats_cap or DEFAULT_STATS_CAP)
        self.sparse_threshold = sparse_threshold
        self.reduce_method = resolve_reduce_method(reduce_method)
        # MXU one-hot reduce (round 23, ops/tiled): auto-resolved from
        # the program's K x B payload width; the sparse frontier's
        # CSR-expand rides the same flag (fr.expand_frontier use_mxu)
        self.use_mxu = resolve_use_mxu(use_mxu, program)
        # Paged two-level gather for the DENSE iterations
        # (ops/pagegather.py): page-binned rows + the Pallas lane
        # shuffle replace the per-edge masked-label gather; the
        # SPARSE path keeps the src-sorted view, like pairs below.
        self.page_plan = None
        self.gather = "flat"
        if gather != "flat":
            if gather in ("paged", "pagemajor") \
                    and pair_threshold is not None:
                raise ValueError(
                    f"gather={gather!r} subsumes pair delivery (both "
                    f"are row-granular layouts); build without "
                    f"pair_threshold")
            if pair_threshold is None:
                from lux_tpu.ops.pagegather import engine_page_plan
                self.page_plan = engine_page_plan(sg, gather, program,
                                                  exchange)
                if self.page_plan is not None:
                    self.gather = self.page_plan.mode
        # Pair-lane delivery for the DENSE iterations (ops/pairs.py):
        # dense pair edges leave the per-edge gather path; the SPARSE
        # path below keeps the FULL graph's src-sorted view — frontier
        # expansion must see every edge.
        self.pairs = None
        dense_sg = sg
        if pair_threshold is not None:
            from lux_tpu.ops.pairs import plan_sharded_pairs
            if layout != "tiled":
                raise ValueError(
                    "pair_threshold requires the tiled layout")
            self.pairs, dense_sg = plan_sharded_pairs(
                sg, pair_threshold, min_fill=pair_min_fill)
        from lux_tpu.ops.pairs import resolve_pair_stream
        from lux_tpu.ops.tiled import STREAM_MSG_BYTES
        self.pair_stream = resolve_pair_stream(pair_stream, self.pairs)
        # stream the dense iterations' gather+relax+partials once the
        # [rows, C, E] candidate temporary passes the budget (same
        # billion-edge OOM as the pull engine; PERF_NOTES ledger)
        rows = len(sg.part_ids())
        self.stream_chunks = (rows * dense_sg.epad * 4 > STREAM_MSG_BYTES
                              if stream_msgs is None
                              else bool(stream_msgs))
        dev = jnp.asarray if mesh is None else np.asarray
        if self.page_plan is not None:
            # the paged plan IS the dense edge layout (sparse
            # iterations keep the src-sorted view added below)
            from lux_tpu.engine.pull import common_graph_arrays
            from lux_tpu.ops.pagegather import plan_graph_arrays
            self.owner = None
            self.tiles = None
            arrays = dict(
                common_graph_arrays(dense_sg, dev),
                **plan_graph_arrays(
                    self.page_plan, dev,
                    owner=exchange == "owner", dot=False,
                    num_parts=sg.num_parts, vpad=sg.vpad))
        elif exchange == "owner":
            # dense iterations run owner-side (ops/owner.py): per-
            # source-part small-shard gathers + reduce_scatter replace
            # the label all_gather + big-table gather; the sparse path
            # below is unchanged (queue exchange is already O(queue))
            from lux_tpu.engine.pull import (_owner_edge_arrays,
                                             common_graph_arrays)
            from lux_tpu.ops.owner import OwnerLayout
            self.owner = OwnerLayout.build(dense_sg, E=owner_tile_e or 256)
            self.tiles = None
            arrays = dict(
                **common_graph_arrays(dense_sg, dev),
                **_owner_edge_arrays(self.owner, dev),
                own_cs=dev(self.owner.chunk_start),
                own_lc=dev(self.owner.last_chunk))
            if self.owner.weight is not None:
                arrays["own_w"] = dev(self.owner.weight)
            if self.owner.streams():
                # fused streamed combine: never materializes [C, W]
                ep, et = self.owner.extract_plan()
                arrays["own_ep"] = dev(ep)
                arrays["own_et"] = dev(et)
        else:
            self.owner = None
            arrays, self.tiles = build_graph_arrays(
                dense_sg, layout, needs_dst=False, tile_w=tile_w,
                tile_e=tile_e, device=mesh is None)
        if self.pairs is not None:
            arrays["pair_rowbind"] = dev(self.pairs.rowbind)
            arrays["pair_rel"] = dev(self.pairs.rel_dst)
            arrays["pair_tile_pos"] = dev(self.pairs.tile_pos)
            if self.pairs.weight is not None:
                arrays["pair_weight"] = dev(self.pairs.weight)
        self.enable_sparse = enable_sparse
        if enable_sparse:
            # The compressed source index's pad size is a compiled
            # SHAPE: on multi-host runs agree on the max across every
            # process's parts.
            s_pad = sg.src_unique_max()
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                s_pad = int(np.max(multihost_utils.process_allgather(
                    np.asarray([s_pad]))))
            ss = sg.src_sorted(s_pad=s_pad)
            # Reference queue sizing rule (push_model.inl:393-397).
            self.queue_cap = frontier_capacity(sg.vpad, sparse_threshold)
            # The edge budget must cover any single vertex's out-edges
            # within one part, or a truncated hub could make zero
            # progress forever (see module docstring).  It is a STATIC
            # shape, so on local-parts (multi-host) builds it must not
            # depend on which parts this process holds — bound it by
            # the global max out-degree instead.
            if sg.local_parts is not None:
                max_deg = int(sg.max_out_degree) or 1
            else:
                max_deg = sg.max_in_deg() or 1
            default_eb = max(1024, sg.epad // sparse_threshold)
            self.edge_budget = int(edge_budget if edge_budget is not None
                                   else max(default_eb, max_deg + 128))
            arrays = dict(arrays,
                          src_ids=dev(ss["src_ids"]),
                          src_off=dev(ss["src_off"]),
                          ss_dst=dev(ss["ss_dst"]),
                          part_start=dev(
                              sg.starts[sg.part_ids()].astype(
                                  np.int32)[:, None]))
            if ss["ss_weight"] is not None:
                arrays["ss_weight"] = dev(ss["ss_weight"])
        if mesh is not None:
            arrays = shard_over_parts(mesh, arrays, sg.num_parts)
        self.arrays = arrays
        # compiled-variant registry for the static program auditor
        # (lux_tpu/audit.py): name -> (jitted fn, example-args thunk)
        self._audit_variants: dict = {}
        self._step_fn = self._build(converge=False)
        self._converge_fn = self._build(converge=True)
        if audit is not None:
            # mode validation lives in audit_engine (typed ValueError
            # on anything but 'warn'/'error')
            from lux_tpu import audit as _audit
            _audit.audit_engine(self, mode=audit)

    # ------------------------------------------------------------------

    def init_state(self):
        pending = self._consume_pending_init()
        if pending is not None:
            label0, active0 = pending
        else:
            label0, active0 = self.program.init(self.sg)
        return self.place(label0, active0)

    def place(self, label, active):
        """Put host (or replicated) state arrays on the engine's
        devices with the parts sharding (used by checkpoint resume).
        Like PullEngine.place, this is the elastic re-placement entry
        point: the global ``[P, vpad]`` label/active views re-shard
        onto whatever mesh THIS engine was built over (round 11)."""
        self._drop_pending_init()     # resume never needs the probe
        if self.mesh is not None:
            return tuple(shard_over_parts(
                self.mesh, [np.asarray(label), np.asarray(active)],
                self.sg.num_parts))
        return jnp.asarray(label), jnp.asarray(active)

    # -- dense iteration over this device's parts ----------------------

    def _dense_flat(self, full_label, full_active):
        """Phase 1 (exchange): mask inactive sources to the identity
        BEFORE the per-edge gather — one gather instead of two (the
        gather is ~90% of a dense iteration, PERF_NOTES.md), with
        identical semantics: relax(identity) stays absorbing for
        min/max programs.  Batched labels [.., vpad, B] keep their
        query axis: the flat table is [P*vpad, B] and the SAME single
        gather fetches all B columns per edge (a retired column is
        all-inactive, so it contributes the identity here exactly
        like any masked source — the sentinel convention per query)."""
        ident_l = jnp.asarray(self.program.identity, full_label.dtype)
        masked = jnp.where(full_active, full_label, ident_l)
        return masked.reshape((-1,) + masked.shape[2:])

    def _dense_cand(self, flat_l, g):
        """Phase 2 (relax): per-edge source gather + candidates."""
        prog = self.program
        ident_l = jnp.asarray(prog.identity, flat_l.dtype)
        src_l = jnp.take(flat_l, g["src_slot"], axis=0)
        cand = prog.relax(src_l, g.get("weight"))
        ident = jnp.asarray(prog.identity, cand.dtype)
        cand = jnp.where(src_l == ident_l, ident, cand)
        return jax.lax.optimization_barrier(cand)

    def _dense_red(self, flat_l, cand, g):
        """Phase 3 (reduce): scatter-free segment reduction (+ the
        pair-lane delivery, which fetches and reduces in one go).
        cand=None: stream gather+relax+partials in chunk blocks
        (billion-edge memory mode; PERF_NOTES ledger)."""
        sg, prog, lay = self.sg, self.program, self.tiles
        # relax + mask masked-source candidates back to the identity
        # (shared by the streamed, pair, paged and owner deliveries)
        msg = self._owner_msg(flat_l.dtype)

        if self.page_plan is not None:
            # paged two-level delivery (ops/pagegather.py): the page
            # fetch + lane shuffle + compare-reduce replace both the
            # masked-label gather and the tiled reduce (pg_vrs: the
            # page-major plan's virtual-row binding)
            from lux_tpu.ops.pagegather import paged_partial
            return paged_partial(
                self.page_plan, flat_l, g["pg_ids"], g["pg_sl"],
                g["pg_rel"], g.get("pg_w"), g["pg_tp"], prog.reduce,
                msg, reduce_method=self.reduce_method,
                vrow_src=g.get("pg_vrs"))[:sg.vpad]
        if cand is None:
            from lux_tpu.ops.tiled import (combine_partials,
                                           streamed_chunk_partials)
            partials = streamed_chunk_partials(
                flat_l, g["src_slot"], g["rel_dst"], g.get("weight"),
                lay, prog.reduce, msg, self.reduce_method,
                use_mxu=self.use_mxu)
            red = combine_partials(partials, lay, g["chunk_start"],
                                   g["last_chunk"], sg.vpad,
                                   prog.reduce, use_mxu=self.use_mxu)
        elif lay is None:
            red = segment_reduce(cand, g["dst_local"], sg.vpad + 1,
                                 prog.reduce)[:sg.vpad]
        else:
            red = tiled_segment_reduce(
                cand, lay, g["chunk_start"], g["last_chunk"],
                g["rel_dst"], sg.vpad, prog.reduce,
                use_mxu=self.use_mxu,
                method=("pallas"
                        if self.reduce_method.startswith("pallas")
                        else "xla"),
                interpret=self.reduce_method == "pallas-interpret")
        if self.pairs is not None:
            from lux_tpu.ops.tiled import combine_op
            red = combine_op(prog.reduce)(
                red, self._pair_red(flat_l, g, msg))
        return red

    def _pair_red(self, flat_l, g, msg):
        """Pair-lane delivery for one part -> [vpad] partial (shared
        by the gather- and owner-exchange dense paths)."""
        from lux_tpu.ops.pairs import (pair_partial,
                                       pair_partial_streamed)

        fn = pair_partial_streamed if self.pair_stream else pair_partial
        return fn(
            self.pairs, flat_l, g["pair_rowbind"], g["pair_rel"],
            g.get("pair_weight"), g["pair_tile_pos"],
            self.program.reduce, msg,
            reduce_method=self.reduce_method)[:self.sg.vpad]

    def _dense_update(self, old, red, g):
        """Phase 4 (update): keep improvements, flag the new frontier
        (per query on batched labels — the [vpad] vertex mask
        broadcasts over the trailing query axis)."""
        vm = vmask_of(g, self.sg.vpad)
        vm = vm.reshape(vm.shape + (1,) * (red.ndim - 1))
        improved = self.program.better(red, old) & vm
        return jnp.where(improved, red, old), improved

    _DENSE_KEYS = ("src_slot", "dst_local", "weight", "rel_dst",
                   "chunk_start", "last_chunk", "chunk_tile", "nvp",
                   "deg", "pair_rowbind", "pair_rel", "pair_weight",
                   "pair_tile_pos", "pg_ids", "pg_sl", "pg_rel",
                   "pg_w", "pg_tp", "pg_vrs")

    @property
    def _streams(self) -> bool:
        return self.stream_chunks and self.tiles is not None

    def _dense_parts(self, label, active, full_label, full_active, g):
        with jax.named_scope("lux_exchange"):
            flat_l = self._dense_flat(full_label, full_active)
        # streamed and paged steps both fuse gather+relax+reduce into
        # one delivery (the paged one: page fetch + lane shuffle +
        # compare-reduce, ops/pagegather.py)
        stream = self._streams or self.page_plan is not None

        def one(old, g):
            with jax.named_scope("lux_relax"):
                cand = None if stream else self._dense_cand(flat_l, g)
            with jax.named_scope("lux_reduce"):
                red = self._dense_red(flat_l, cand, g)
            with jax.named_scope("lux_update"):
                return self._dense_update(old, red, g)

        g = {k: g[k] for k in self._DENSE_KEYS if k in g}
        return jax.vmap(one)(label, g)

    # -- dense iteration, owner-side exchange (ops/owner.py) -----------

    def _owner_msg(self, label_dtype):
        """relax + mask identity-source candidates back to the
        identity (same contract as _dense_cand/_dense_red's msg)."""
        prog = self.program
        ident_l = jnp.asarray(prog.identity, label_dtype)

        def msg(vals, w):
            c = prog.relax(vals, w)
            return jnp.where(vals == ident_l,
                             jnp.asarray(prog.identity, c.dtype), c)

        return msg

    def _dense_parts_owner(self, label, active, g):
        """One dense iteration with owner-side message generation:
        each LOCAL source part masks its own label shard (inactive ->
        identity, exactly _dense_flat's one-gather trick applied per
        shard), gathers from it under the lax.scan, and routes
        per-dst-part candidates through the all_to_all exchange —
        no label/active all_gather at all (except for pair rows)."""
        from lux_tpu.ops.owner import owner_contribs, owner_exchange

        sg, prog = self.sg, self.program
        on_mesh = self.mesh is not None
        ident_l = jnp.asarray(prog.identity, label.dtype)
        masked = jnp.where(active, label, ident_l)
        msg = self._owner_msg(label.dtype)
        msg_dtype = jax.eval_shape(
            msg, jax.ShapeDtypeStruct((1, 1), label.dtype),
            (jax.ShapeDtypeStruct((1, 1), jnp.float32)
             if ("own_w" in g or "own_pg_w" in g or "own_pm_w" in g)
             else None)).dtype
        with jax.named_scope("lux_gen_exchange"):
            if (self.page_plan is not None
                    and self.page_plan.mode == "pagemajor"):
                # page-major routing: complete message rows all_to_all
                # to their destination parts, reduced receiver-side
                # (ops/pagegather.pagemajor_owner_deliver) — the
                # routing hop REPLACES the owner exchange
                from lux_tpu.ops.pagegather import \
                    pagemajor_owner_deliver
                red = pagemajor_owner_deliver(
                    self.page_plan, masked, g, prog.reduce, msg,
                    msg_dtype, sg.num_parts, self.reduce_method,
                    axis=PARTS_AXIS if on_mesh else None,
                    varying_axis=PARTS_AXIS if on_mesh else None)
            else:
                if self.page_plan is not None:
                    from lux_tpu.ops.pagegather import \
                        paged_owner_contribs
                    acc = paged_owner_contribs(
                        self.page_plan, masked, g, prog.reduce, msg,
                        msg_dtype, sg.num_parts, self.reduce_method,
                        varying_axis=PARTS_AXIS if on_mesh else None)
                else:
                    acc = owner_contribs(
                        self.owner, masked, g,
                        prog.reduce, msg, msg_dtype, sg.num_parts,
                        self.reduce_method, use_mxu=self.use_mxu,
                        varying_axis=PARTS_AXIS if on_mesh else None)
                red = owner_exchange(
                    acc, prog.reduce,
                    axis=PARTS_AXIS if on_mesh else None,
                    ndev=1 if not on_mesh else self.mesh.devices.size,
                    minmax_fused=self.owner_minmax_fused)
        red = red[:, :sg.vpad]
        if self.pairs is not None:
            # pair rows fetch from the FULL masked table (row-granular
            # fetches); the all_gather survives only for them
            from lux_tpu.ops.tiled import combine_op

            full = (masked if not on_mesh else
                    jax.lax.all_gather(masked, PARTS_AXIS, tiled=True))
            flat_l = full.reshape(-1)
            pkeys = [k for k in ("pair_rowbind", "pair_rel",
                                 "pair_weight", "pair_tile_pos")
                     if k in g]
            pred = jax.vmap(
                lambda gp: self._pair_red(flat_l, gp, msg))(
                {k: g[k] for k in pkeys})
            red = combine_op(prog.reduce)(red, pred)
        gd = {k: g[k] for k in self._DENSE_KEYS if k in g}
        return jax.vmap(self._dense_update)(label, red, gd)

    # -- sparse iteration ----------------------------------------------

    def _sparse_parts(self, label, active, g, gather_fn, pmin_fn):
        """One frontier-queue iteration over this device's parts.

        gather_fn concatenates per-part queue arrays across the whole
        mesh (identity + reshape on a single device); pmin_fn reduces a
        scalar with min across the mesh.
        """
        sg, prog = self.sg, self.program
        Q, EB = self.queue_cap, self.edge_budget
        nv = sg.nv

        # 1. compact each local part's mask into a (global id, label)
        #    queue.
        def compact(mask, lab, start):
            ids, vals, cnt = fr.compact_mask(mask, lab, Q)
            gids = jnp.where(ids < sg.vpad, start[0] + ids, nv)
            return gids.astype(jnp.int32), vals, cnt

        gids, vals, cnts = jax.vmap(compact)(
            active, label, g["part_start"])

        # 2. exchange queues: [P_total * Q] flat, part-major order
        #    (identical on every device).
        all_gids = gather_fn(gids).reshape(-1)
        all_vals = gather_fn(vals).reshape(-1)

        # 3. each part relaxes the gathered frontier's edges that land
        #    in its partition, through its compressed src-sorted view.
        def relax_part(lab, sids, soff, ssd, ssw):
            edge_idx, src_val, in_range, _total, off = fr.expand_frontier(
                all_gids, all_vals, sids, soff, nv, EB,
                use_mxu=self.use_mxu)
            dst = jnp.take(ssd, edge_idx, axis=0)
            w = jnp.take(ssw, edge_idx, axis=0) if ssw is not None \
                else None
            cand = prog.relax(src_val, w)
            ident = jnp.asarray(prog.identity, cand.dtype)
            cand = jnp.where(in_range & (dst < sg.vpad), cand, ident)
            dst = jnp.where(in_range, dst, sg.vpad - 1)
            new = fr.scatter_reduce(lab, dst, cand, prog.reduce)
            improved = prog.better(new, lab)
            # number of fully-expanded queue items (flat prefix)
            done = jnp.searchsorted(off, jnp.asarray(EB, off.dtype),
                                    side="right",
                                    method="scan_unrolled")
            return new, improved, done.astype(jnp.int32)

        ssw = g.get("ss_weight")
        if ssw is None:
            new_label, improved, done = jax.vmap(
                lambda lab, sids, soff, ssd: relax_part(
                    lab, sids, soff, ssd, None))(
                label, g["src_ids"], g["src_off"], g["ss_dst"])
        else:
            new_label, improved, done = jax.vmap(relax_part)(
                label, g["src_ids"], g["src_off"], g["ss_dst"], ssw)
        improved = improved & vmask_of(g, sg.vpad)

        # 4. clear the globally-agreed processed prefix of the queue;
        #    everything else stays active (truncation safety).
        done_min = pmin_fn(jnp.min(done))

        # ids are global; convert back to local slots for clearing
        def clear_local(mask, gid, cnt, start, pidx):
            pos = jnp.arange(Q, dtype=jnp.int32)
            flat_base = pidx * Q
            processed = (flat_base + pos < done_min) & (pos < cnt) & \
                (gid < nv)
            loc = jnp.clip(gid - start[0], 0, sg.vpad - 1)
            upd = jnp.zeros((sg.vpad,), bool).at[loc].max(
                processed, mode="drop")
            return mask & ~upd

        pidx = self._part_index()
        cleared = jax.vmap(clear_local)(active, gids, cnts,
                                        g["part_start"], pidx)
        new_active = improved | cleared
        return new_label, new_active

    def _part_index(self):
        """Global part index of this device's parts [P_local] int32."""
        P_local = self.sg.num_parts if self.mesh is None else \
            self.sg.num_parts // self.mesh.devices.size
        base = jnp.int32(0)
        if self.mesh is not None:
            base = jax.lax.axis_index(PARTS_AXIS) * P_local
        return base + jnp.arange(P_local, dtype=jnp.int32)

    # -- compiled whole-run / single-step ------------------------------

    def _build(self, converge: bool, stats: bool = False,
               health: bool = False):
        """stats=True (converge only) additionally accumulates
        device-side per-iteration counters INSIDE the while_loop into
        fixed [stats_cap] buffers: frontier size (int32) and frontier
        out-edges relaxed (uint32) per iteration — see
        lux_tpu/telemetry.py for the exact semantics.  Out-degrees
        come from the FULL graph (self.sg, pair rows included), passed
        as one extra sharded argument so the counter-free program
        never carries them.  Round 13: the same variant ALSO records
        the per-part split into [stats_cap, P] buffers (frontier and
        out-edges per part; the scalar entries are the SUMS of the
        per-part rows, so sum-over-parts is bitwise-exact by
        construction) — per-part values are reduced per local part
        and all_gathered over the mesh (P ints per iteration over
        ICI), adding NO state-table gathers (audit gather-budget
        stays at the same budget).

        health=True (implies stats) additionally accumulates the O(1)
        health word (lux_tpu/health.py: NaN labels — +Inf stays the
        legitimate unreached sentinel — and the truncation-livelock
        frontier stall) and EXITS the while_loop the iteration a check
        trips, so a livelocked run stops instead of spinning to
        max_iters."""
        assert not stats or converge
        assert not health or stats
        keys = sorted(self.arrays)
        graph_args = tuple(self.arrays[k] for k in keys)
        on_mesh = self.mesh is not None
        sg, prog = self.sg, self.program
        use_sparse, sparse_limit = self._sparse_mode()
        cap_n = self.stats_cap

        def global_sum(x):
            s = jnp.sum(x)
            if on_mesh:
                s = jax.lax.psum(s, PARTS_AXIS)
            return s

        def gather_fn(x):
            if on_mesh:
                return jax.lax.all_gather(x, PARTS_AXIS, tiled=True)
            return x

        def pmin_fn(x):
            if on_mesh:
                return jax.lax.pmin(x, PARTS_AXIS)
            return x

        if health:
            from lux_tpu import health as hw
            P_local = (sg.num_parts if not on_mesh
                       else sg.num_parts // self.mesh.devices.size)
            _BIG = jnp.int32(np.iinfo(np.int32).max)

            def health_step(h, stall, old_l, new_l, old_cnt,
                            new_cnt):
                """One relax iteration's health update (runs INSIDE
                shard_map — everything psum/pmin'd so the word is
                identical on every device)."""
                badp = hw.nan_parts(new_l)          # [P_local] int32
                nf = global_sum(badp)
                chg = global_sum((new_l != old_l).astype(jnp.int32))
                base = jnp.int32(0)
                if on_mesh:
                    base = (jax.lax.axis_index(PARTS_AXIS)
                            * jnp.int32(P_local))
                loc = hw.first_bad_part(badp)
                cand = pmin_fn(jnp.where(loc >= 0, base + loc, _BIG))
                part = jnp.where(cand == _BIG, -1,
                                 cand).astype(jnp.int32)
                # truncation livelock: non-empty frontier, identical
                # active count, bit-identical labels — for STALL_N
                # consecutive relax steps (a zero-progress step that
                # SHRINKS the active set is legitimate and resets)
                stalled = ((chg == 0) & (new_cnt > 0)
                           & (new_cnt == old_cnt))
                stall = jnp.where(stalled, stall + jnp.int32(1),
                                  jnp.int32(0))
                flags = ((nf > 0) * hw.NONFINITE_STATE
                         + (stall >= hw.STALL_N) * hw.FRONTIER_STALL)
                return hw.record(h, flags, part, nf, new_cnt), stall

        def dense_body(label, active, g):
            if self.exchange == "owner":
                return self._dense_parts_owner(label, active, g)
            if on_mesh:
                full_l = jax.lax.all_gather(label, PARTS_AXIS, tiled=True)
                full_a = jax.lax.all_gather(active, PARTS_AXIS, tiled=True)
            else:
                full_l, full_a = label, active
            return self._dense_parts(label, active, full_l, full_a, g)

        def body(label, active, count, g):
            if not use_sparse:
                return dense_body(label, active, g)

            # Reference heuristic: frontier > nv/16 -> dense/pull mode
            # (sssp_gpu.cu:414), and the queue must fit (_sparse_mode).
            def sparse_branch():
                with jax.named_scope("lux_sparse"):
                    return self._sparse_parts(label, active, g,
                                              gather_fn, pmin_fn)

            def dense_branch():
                with jax.named_scope("lux_dense"):
                    return dense_body(label, active, g)

            q_fits = count <= jnp.int32(sparse_limit)
            return jax.lax.cond(q_fits, sparse_branch, dense_branch)

        use_delta = converge and self.delta is not None

        def inner(label, active, max_iters, *gargs):
            if health:
                # previous segment's watchdog carry (word + stall
                # counter) — threaded so a stall spanning a segment
                # boundary still accumulates
                h0, stall0, gargs = gargs[0], gargs[1], gargs[2:]
            if stats:
                deg_full, gargs = gargs[0], gargs[1:]
            g = dict(zip(keys, gargs))

            def esum_parts(act):
                # out-edges of the frontier ``act`` PER PART [P] —
                # the relax work each part contributes this iteration
                # (replicated via all_gather on a mesh: P ints per
                # iteration over ICI, no state-table gathers).
                # uint32: a full 2^31+-edge frontier must not wrap
                # int32; the scalar counter is the SUM of this row,
                # so sum-over-parts is bitwise-exact by construction.
                # Batched labels: the dense iteration gathers each
                # edge ONCE for all B queries, so the work counter is
                # the out-edges of the UNION frontier over the query
                # axis (any column active at the vertex).
                if act.ndim > 2:
                    act = jnp.any(act, axis=-1)
                e = jnp.sum(jnp.where(act, deg_full, 0)
                            .astype(jnp.uint32), axis=1)
                if on_mesh:
                    e = jax.lax.all_gather(e, PARTS_AXIS, tiled=True)
                return e

            def fcount_parts(act):
                # active count per part [P] int32 (sums to the psum'd
                # scalar frontier count exactly — integer addition);
                # batched: active (vertex, query) PAIRS, matching the
                # scalar global_sum the convergence predicate uses
                c = jnp.sum(act.astype(jnp.int32),
                            axis=tuple(range(1, act.ndim)))
                if on_mesh:
                    c = jax.lax.all_gather(c, PARTS_AXIS, tiled=True)
                return c

            if not converge:
                cnt0 = global_sum(active)
                new_label, new_active = body(label, active, cnt0, g)
                return new_label, new_active, global_sum(new_active)

            if use_delta:
                # Delta-stepping (Meyer & Sanders): relax only the
                # current distance bucket [*, B) to (near-)settlement
                # before advancing B — fewer wasted re-relaxations of
                # far vertices than plain Bellman-Ford frontiers.  One
                # XLA while_loop; bucket advance is a pmin'd scalar.
                ident = jnp.asarray(prog.identity, label.dtype)
                delta = jnp.asarray(self.delta, label.dtype)

                def active_min(lbl, act):
                    m = jnp.min(jnp.where(act, lbl, ident))
                    if on_mesh:
                        m = jax.lax.pmin(m, PARTS_AXIS)
                    return m

                # `it` counts RELAX iterations only (what max_iters
                # caps and what GTEPS reporting uses); bucket advances
                # relax nothing and are not iterations.  Advance-only
                # stretches terminate on their own: while any vertex is
                # active, raising B eventually makes the frontier
                # non-empty.
                def cond(c):
                    it, lbl, act, B, cnt = c[:5]
                    ok = (cnt > 0) & (it < max_iters)
                    if health:        # exit the loop on a tripped word
                        ok = ok & (c[9][0] == 0)
                    return ok

                def wbody(c):
                    it, lbl, act, B, cnt = c[:5]
                    buf = c[5:]
                    front = act & (lbl < B)
                    nf = global_sum(front)

                    def relax(it, lbl, act, B, *buf):
                        if stats:
                            # counters record the bucket front ENTERING
                            # this relax — the series timed_phases'
                            # delta schedule reports; advances relax
                            # nothing and write no entry.  The scalar
                            # edges entry is the sum of the per-part
                            # row (bitwise, uint32 either way).
                            fsz, fed, fszp, fedp = buf[:4]
                            ep = esum_parts(front)
                            buf = (fsz.at[it].set(nf, mode="drop"),
                                   fed.at[it].set(jnp.sum(ep),
                                                  mode="drop"),
                                   fszp.at[it].set(fcount_parts(front),
                                                   mode="drop"),
                                   fedp.at[it].set(ep, mode="drop")) \
                                + buf[4:]
                        nl, na = body(lbl, front, nf, g)
                        merged = (act & ~front) | na
                        if health:
                            # the watchdog watches relax steps only:
                            # advances relax nothing and terminate on
                            # their own (see `advance` below)
                            h, stall = health_step(
                                buf[4], buf[5], lbl, nl, cnt,
                                global_sum(merged))
                            buf = buf[:4] + (h, stall)
                        return (it + 1, nl, merged, B, *buf)

                    def advance(it, lbl, act, B, *buf):
                        # Strict progress: with float labels a delta
                        # below one ulp at the current magnitude makes
                        # active_min + delta round back to active_min
                        # and the advance loop livelocks (frontier
                        # stays empty forever).  Raising B strictly
                        # above active_min guarantees the argmin active
                        # vertex enters the next frontier.
                        am = active_min(lbl, act)
                        nb = am + delta
                        if jnp.issubdtype(label.dtype, jnp.inexact):
                            nb = jnp.maximum(
                                nb, jnp.nextafter(
                                    am, jnp.asarray(jnp.inf, am.dtype)))
                        return it, lbl, act, nb, *buf

                    out = jax.lax.cond(
                        nf > 0, relax, advance, it, lbl, act, B, *buf)
                    it, lbl, act, B = out[:4]
                    return (it, lbl, act, B, global_sum(act), *out[4:])

                B0 = active_min(label, active) + delta
                init = (jnp.int32(0), label, active, B0,
                        global_sum(active))
                if stats:
                    init = init + (
                        jnp.zeros((cap_n,), jnp.int32),
                        jnp.zeros((cap_n,), jnp.uint32),
                        jnp.zeros((cap_n, sg.num_parts), jnp.int32),
                        jnp.zeros((cap_n, sg.num_parts), jnp.uint32))
                if health:
                    init = init + (h0, stall0)
                out = jax.lax.while_loop(cond, wbody, init)
                it, lbl, act = out[0], out[1], out[2]
                if health:
                    return lbl, act, it, out[5], out[6], out[7], \
                        out[8], out[9], out[10]
                if stats:
                    return lbl, act, it, out[5], out[6], out[7], \
                        out[8]
                return lbl, act, it

            def cond(c):
                it, lbl, act, cnt = c[:4]
                ok = (cnt > 0) & (it < max_iters)
                if health:            # exit the loop on a tripped word
                    ok = ok & (c[8][0] == 0)
                return ok

            def wbody(c):
                it, lbl, act, cnt = c[:4]
                if stats:
                    fsz, fed, fszp, fedp = c[4:8]
                    # edges relaxed by THIS iteration: out-edges of
                    # the frontier entering it, per part; the scalar
                    # is the row's sum (bitwise-exact, uint32)
                    ep = esum_parts(act)
                    fed = fed.at[it].set(jnp.sum(ep), mode="drop")
                    fedp = fedp.at[it].set(ep, mode="drop")
                nl, na = body(lbl, act, cnt, g)
                ncnt = global_sum(na)
                if stats:
                    # frontier AFTER the iteration — exactly the
                    # series the stepwise -verbose path printed
                    fsz = fsz.at[it].set(ncnt, mode="drop")
                    fszp = fszp.at[it].set(fcount_parts(na),
                                           mode="drop")
                    if health:
                        h, stall = health_step(c[8], c[9], lbl,
                                               nl, cnt, ncnt)
                        return (it + 1, nl, na, ncnt, fsz, fed, fszp,
                                fedp, h, stall)
                    return it + 1, nl, na, ncnt, fsz, fed, fszp, fedp
                return it + 1, nl, na, ncnt

            it0 = jnp.int32(0)
            cnt0 = global_sum(active)
            init = (it0, label, active, cnt0)
            if stats:
                init = init + (
                    jnp.zeros((cap_n,), jnp.int32),
                    jnp.zeros((cap_n,), jnp.uint32),
                    jnp.zeros((cap_n, sg.num_parts), jnp.int32),
                    jnp.zeros((cap_n, sg.num_parts), jnp.uint32))
            if health:
                init = init + (h0, stall0)
            out = jax.lax.while_loop(cond, wbody, init)
            it, lbl, act = out[0], out[1], out[2]
            if health:
                return lbl, act, it, out[4], out[5], out[6], out[7], \
                    out[8], out[9]
            if stats:
                return lbl, act, it, out[4], out[5], out[6], out[7]
            return lbl, act, it

        if prog.name:
            inner = jax.named_scope(f"lux_{prog.name}")(inner)
        if on_mesh:
            P = PartitionSpec
            out_specs = (P(PARTS_AXIS), P(PARTS_AXIS), P())
            if stats:
                # counters are psum/all_gather-replicated values
                # written into replicated buffers (scalar pair + the
                # per-part [cap, P] pair)
                out_specs = out_specs + (P(), P(), P(), P())
            if health:
                # the health word + stall counter are built from
                # psum/pmin'd scalars, identical on every device
                out_specs = out_specs + (P(), P())
            in_specs = (P(PARTS_AXIS), P(PARTS_AXIS), P())
            if health:
                in_specs = in_specs + (P(), P())    # h0, stall0
            in_specs = in_specs + \
                (P(PARTS_AXIS),) * (len(keys) + int(stats))
            inner = jax.shard_map(inner, mesh=self.mesh,
                                  in_specs=in_specs,
                                  out_specs=out_specs)

        jitted = jax.jit(inner, donate_argnums=(0, 1))

        extra = ()
        if stats:
            deg_full = np.asarray(self.sg.deg_padded)
            if self.mesh is not None:
                deg_full = shard_over_parts(self.mesh, [deg_full],
                                            self.sg.num_parts)[0]
            else:
                deg_full = jnp.asarray(deg_full)
            extra = (deg_full,)

        vname = ("converge" if converge else "step") + \
            ("_health" if health else "_stats" if stats else "")

        def _args_thunk():
            lab_sds, act_sds = self._audit_state_sds
            watch = ()
            if health:
                from lux_tpu import health as _hw0
                watch = (_hw0.init_word(), jnp.int32(0))
            return (lab_sds, act_sds,
                    jax.ShapeDtypeStruct((), jnp.int32),
                    *watch, *extra, *graph_args)

        self._register_variant(vname, jitted, _args_thunk)

        if health:
            from lux_tpu import health as _hw

            def call(label, active, max_iters=np.iinfo(np.int32).max,
                     watch=None):
                if watch is None:
                    watch = (_hw.init_word(), jnp.int32(0))
                l, a, it, fsz, fed, fszp, fedp, h, stall = jitted(
                    label, active, jnp.int32(max_iters), *watch,
                    *extra, *graph_args)
                return l, a, it, fsz, fed, fszp, fedp, (h, stall)

            return call

        def call(label, active, max_iters=np.iinfo(np.int32).max):
            return jitted(label, active, jnp.int32(max_iters), *extra,
                          *graph_args)

        return call

    # -- static-audit surface (engine/auditable.py) --------------------

    _AUDIT_LAZY = ("_converge_stats_fn", "_converge_health_fn")

    # timed_phases phases whose measured seconds CONTAIN the dense
    # iteration's collectives (label/active all_gather rides the
    # exchange phase, the owner routing rides gen_exchange; sparse
    # queue exchanges are timed as one whole program and carry no
    # phase split) — the comm observatory's attribution anchor
    # (lux_tpu/comms.py, observe._comm_attribution)
    COMM_PHASES = ("exchange", "gen_exchange")

    @functools.cached_property
    def _audit_state_sds(self):
        """Abstract (label, active) stand-ins — init runs ONCE per
        engine, not once per audited variant, and the materialized
        arrays are stashed for the next ``init_state`` call so an
        audited-then-run engine pays for exactly one host init."""
        lab0, act0 = self.program.init(self.sg)
        lab0, act0 = np.asarray(lab0), np.asarray(act0)
        self._pending_init = (lab0, act0)
        return (jax.ShapeDtypeStruct(lab0.shape, lab0.dtype),
                jax.ShapeDtypeStruct(act0.shape, act0.dtype))

    # -- public API ----------------------------------------------------

    def step(self, label, active):
        """One compiled iteration -> (label, active, global active count
        as a device scalar)."""
        return self._step_fn(label, active)

    def converge(self, label, active, max_iters: int | None = None):
        """Run to an empty frontier inside ONE XLA program.
        Returns (label, active, iterations_executed)."""
        cap = np.iinfo(np.int32).max if max_iters is None else max_iters
        return self._converge_fn(label, active, cap)

    @functools.cached_property
    def _converge_stats_fn(self):
        return self._build(converge=True, stats=True)

    def converge_stats(self, label, active,
                       max_iters: int | None = None):
        """``converge`` + device-side iteration counters accumulated
        INSIDE the fused while_loop (compiled lazily on first use —
        the counter-free program is untouched).  Returns (label,
        active, iters, frontier int32 [stats_cap], edges uint32
        [stats_cap], frontier_parts int32 [stats_cap, P], edges_parts
        uint32 [stats_cap, P]): classic engines record the
        post-iteration frontier size (the stepwise -verbose series)
        and the entering frontier's out-edge count; delta engines
        record each relax step's bucket-front size and out-edges (see
        lux_tpu/telemetry.py).  The per-part counters are the round-13
        imbalance-attribution signal: each scalar entry is the SUM of
        its per-part row, bitwise (tests/test_telemetry.py holds the
        NumPy per-part oracle).  Writes past ``stats_cap`` drop;
        entries past ``iters`` are zero.  Fetch the buffers once per
        run/segment (a few KB) — never inside a timed region's hot
        loop."""
        cap = np.iinfo(np.int32).max if max_iters is None else max_iters
        return self._converge_stats_fn(label, active, cap)

    @functools.cached_property
    def _converge_health_fn(self):
        return self._build(converge=True, stats=True, health=True)

    def converge_health(self, label, active,
                        max_iters: int | None = None, watch=None):
        """``converge_stats`` under the device-side health watchdog
        (lux_tpu/health.py): returns (label, active, iters, frontier
        buf, edges buf, frontier-parts buf, edges-parts buf, watch)
        with watch = (health int32[6], stall counter) — the per-part
        counters ride this variant too, same oracle contract as
        ``converge_stats``.  The while_loop EXITS the iteration a check trips
        (NaN labels; the truncation-livelock frontier stall), so
        ``iters`` then counts only the completed healthy iterations;
        fetch + decode the word once per run/segment with
        ``health.ensure_ok(watch)``, and pass the previous segment's
        ``watch`` back in so a stall spanning a boundary still
        accumulates.  Compiled lazily — the watchdog-free programs
        are untouched."""
        cap = np.iinfo(np.int32).max if max_iters is None else max_iters
        return self._converge_health_fn(label, active, cap, watch)

    def run(self, max_iters: int | None = None, verbose: bool = False,
            seg_budget: float | None = None):
        """init -> converge -> host label array [nv]; returns
        (labels, num_iters).  verbose=True REPLAYS per-iteration
        frontier sizes from the fused run's device-side counters
        (``converge_stats``) — the old stepwise slow path is gone, and
        delta engines replay their ACTUAL bucket schedule's relax
        steps.  seg_budget (seconds) converges in duration-budgeted
        while_loop slices (segmented.DurationBudget) so each XLA
        execution stays under the tunnel's ~55 s crash envelope
        (PERF_NOTES round 5) — counters then accumulate across
        segments, so seg_budget and verbose compose."""
        import contextlib

        from lux_tpu import telemetry
        label, active = self.init_state()
        tel = telemetry.current()
        st = tel.iter_stats
        ctx = contextlib.nullcontext()
        if verbose and st is None:
            st = telemetry.IterStats()
            ctx = telemetry.use(events=tel.events, iter_stats=st)
        with ctx:
            if seg_budget is not None:
                from lux_tpu.segmented import DurationBudget, \
                    converge_segments
                label, active, it = converge_segments(
                    self, label, active,
                    DurationBudget(seg_budget, per_size_compile=False),
                    max_iters)
            elif self.health:
                from lux_tpu import health as hw
                label, active, itd, fsz, fed, fszp, fedp, h = \
                    self.converge_health(label, active, max_iters)
                it = int(jax.device_get(itd))
                if st is not None:
                    st.begin_run()
                    st.extend_push(fsz, fed, it, fszp, fedp)
                hw.ensure_ok(h, engine="push", where="push converge")
            elif st is not None:
                st.begin_run()
                label, active, itd, fsz, fed, fszp, fedp = \
                    self.converge_stats(label, active, max_iters)
                it = int(jax.device_get(itd))
                st.extend_push(fsz, fed, it, fszp, fedp)
            else:
                label, active, itd = self.converge(label, active,
                                                   max_iters)
                it = int(jax.device_get(itd))
        if verbose:
            for line in st.replay_lines():
                print(line)
        return self.unpad(label), it

    def unpad(self, state) -> np.ndarray:
        from lux_tpu.parallel.multihost import fetch_global
        return self.sg.from_padded(fetch_global(state))

    # -- per-iteration phase observability ----------------------------

    @functools.cached_property
    def _phase_jits(self):
        """Per-phase compiled programs for DENSE iterations (exchange /
        relax / reduce / update), each returning (output, scalar fence)
        — see PullEngine._phase_jits.  Sparse iterations are timed as
        one program (their latency is queue-sized, not phase-bound)."""
        from lux_tpu.engine.phased import cksum, mesh_wrap

        keys = sorted(self.arrays)
        sg = self.sg
        dkeys = [k for k in self._DENSE_KEYS if k in self.arrays]

        def gdict(gargs):
            g = dict(zip(keys, gargs))
            return {k: g[k] for k in dkeys}

        if self.exchange == "owner":
            # owner mode has no separable gather phase: generation
            # (scan over source parts) + reduce_scatter are one fused
            # phase; update keeps its frontier-count fence
            def gen_exchange(label, active, *gargs):
                g = dict(zip(keys, gargs))
                new, improved = self._dense_parts_owner(label, active,
                                                        g)
                cnt = jnp.sum(improved.astype(jnp.int32))
                if self.mesh is not None:
                    cnt = jax.lax.psum(cnt, PARTS_AXIS)
                return (new, improved), cnt

            fns = dict(gen_exchange=gen_exchange)
            if self.mesh is not None:
                P = PartitionSpec
                S, R = P(PARTS_AXIS), P()
                wrap = mesh_wrap(self.mesh, len(keys), S, R)
                fns = dict(gen_exchange=wrap(gen_exchange, (S, S),
                                             (S, S)))
            return {k: jax.jit(f) for k, f in fns.items()}

        def exchange(label, active, *gargs):
            full_l, full_a = label, active
            if self.mesh is not None:
                full_l = jax.lax.all_gather(label, PARTS_AXIS, tiled=True)
                full_a = jax.lax.all_gather(active, PARTS_AXIS,
                                            tiled=True)
            flat_l = self._dense_flat(full_l, full_a)
            return flat_l, cksum(flat_l)

        def relax(flat_l, *gargs):
            g = gdict(gargs)
            cand = jax.vmap(
                lambda gp: self._dense_cand(flat_l, gp))(g)
            return cand, cksum(cand)

        def reduce(flat_l, cand, *gargs):
            g = gdict(gargs)
            red = jax.vmap(
                lambda c, gp: self._dense_red(flat_l, c, gp))(cand, g)
            return red, cksum(red)

        def relax_reduce(flat_l, *gargs):
            # streamed engines fuse gather+relax+partials per chunk
            # block; instrument it as ONE phase so the report matches
            # the compiled step (and keeps its memory bound)
            g = gdict(gargs)
            red = jax.vmap(
                lambda gp: self._dense_red(flat_l, None, gp))(g)
            return red, cksum(red)

        def update(label, red, *gargs):
            g = gdict(gargs)
            new, improved = jax.vmap(self._dense_update)(label, red, g)
            # fence doubles as the NEW global frontier count (psum'd
            # under the mesh wrap's pmin — identical on every device).
            # int32 keeps it exact past 2^24 active vertices (float32
            # would round, misreporting 'frontier' and possibly the
            # next iteration's sparse/dense classification)
            cnt = jnp.sum(improved.astype(jnp.int32))
            if self.mesh is not None:
                cnt = jax.lax.psum(cnt, PARTS_AXIS)
            return (new, improved), cnt

        streams = self._streams or self.page_plan is not None
        if streams:
            fns = dict(exchange=exchange, relax_reduce=relax_reduce,
                       update=update)
        else:
            fns = dict(exchange=exchange, relax=relax, reduce=reduce,
                       update=update)
        if self.mesh is not None:
            P = PartitionSpec
            S, R = P(PARTS_AXIS), P()
            wrap = mesh_wrap(self.mesh, len(keys), S, R)
            fns = dict(exchange=wrap(exchange, (S, S), R),
                       update=wrap(update, (S, S), (S, S)),
                       **({"relax_reduce": wrap(relax_reduce, (R,), S)}
                          if streams else
                          {"relax": wrap(relax, (R,), S),
                           "reduce": wrap(reduce, (R, S), S)}))
        return {k: jax.jit(f) for k, f in fns.items()}

    def _sparse_mode(self):
        """Single source of truth for the sparse-vs-dense choice (also
        traced inside the compiled step, _build's q_fits): returns
        (usable, count_limit) — the reference's frontier > nv/16 pull
        switch (sssp_gpu.cu:414) AND the queue capacity."""
        usable = (self.enable_sparse
                  and self.program.reduce in ("min", "max"))
        limit = min(self.queue_cap,
                    max(1, self.sg.nv // self.sparse_threshold)) \
            if self.enable_sparse else 0
        return usable, limit

    def _relax_once(self, label, active, cnt, t, jits, gargs):
        """One instrumented relaxation of ``active``, recording phase
        seconds into ``t``.  Returns (label, na, new_count) where
        ``na`` is the raw improvement/queue-residue mask — the plain
        schedule uses it as the next frontier directly; the delta
        schedule merges it into its own active set."""
        import time as _time

        from lux_tpu.engine.phased import PhaseTimer
        from lux_tpu.timing import fetch

        use_sparse, sparse_limit = self._sparse_mode()
        if use_sparse and cnt <= sparse_limit:
            t0 = _time.perf_counter()
            label, na, c = self.step(label, active)
            cnt = int(fetch(c))
            t["sparse"] = _time.perf_counter() - t0
            return label, na, cnt
        pt = PhaseTimer(fetch)
        pt.t = t
        if "gen_exchange" in jits:            # owner dense: one phase
            label, na = pt("gen_exchange", jits["gen_exchange"],
                           label, active, *gargs)
            return label, na, int(pt.last_fence)
        flat_l = pt("exchange", jits["exchange"], label, active, *gargs)
        if "relax_reduce" in jits:            # streamed: one phase
            red = pt("relax_reduce", jits["relax_reduce"], flat_l,
                     *gargs)
        else:
            cand = pt("relax", jits["relax"], flat_l, *gargs)
            red = pt("reduce", jits["reduce"], flat_l, cand, *gargs)
        label, na = pt("update", jits["update"], label, red, *gargs)
        return label, na, int(pt.last_fence)  # update fence = count

    def timed_phases(self, label, active, iters: int = 1):
        """Instrumented stepwise iterations -> (label, active,
        [{phase: seconds, 'frontier': count}]) — the analogue of the
        reference's per-iteration per-part loadTime/compTime/updateTime
        prints (reference sssp_gpu.cu:513-518).  Dense iterations split
        into exchange/relax/reduce/update (owner exchange:
        gen_exchange); iterations the engine would run sparse are timed
        as one 'sparse' entry.  Delta engines instrument the ACTUAL
        delta-stepping bucket schedule (each entry also records the
        bucket bound and how many relax-free bucket advances preceded
        it).  Separate fenced programs: use for relative weight, not
        GTEPS."""
        from lux_tpu.timing import fetch
        jits = self._phase_jits
        gargs = tuple(self.arrays[k] for k in sorted(self.arrays))
        if self.delta is not None:
            return self._timed_phases_delta(label, active, iters, jits,
                                            gargs)
        count = jax.jit(lambda a: jnp.sum(a.astype(jnp.int32)))
        report = []
        cnt = int(fetch(count(active)))
        for _ in range(iters):
            t = {"frontier": cnt}
            label, active, cnt = self._relax_once(label, active, cnt,
                                                  t, jits, gargs)
            report.append(t)
        return label, active, report

    def _timed_phases_delta(self, label, active, iters, jits, gargs):
        """Instrumented DELTA-STEPPING iterations: replicates the
        compiled converge's bucket schedule (relax the current bucket
        [*, B); advance B past the active minimum when the bucket
        frontier empties) with host-orchestrated fenced phases —
        closing the round-2 observability hole where -phases timed a
        different algorithm than the delta bench ran."""
        from lux_tpu.timing import fetch

        prog = self.program
        ident = prog.identity
        ldt = np.asarray(ident).dtype
        delta_v = np.asarray(self.delta, ldt)

        @jax.jit
        def act_stats(lbl, act):
            am = jnp.min(jnp.where(act, lbl, jnp.asarray(ident,
                                                         lbl.dtype)))
            return am, jnp.sum(act.astype(jnp.int32))

        @jax.jit
        def front_of(lbl, act, B):
            front = act & (lbl < B)
            return front, jnp.sum(front.astype(jnp.int32))

        # split the merge around the relax: the sparse step DONATES
        # its active (= front) buffer, so compute act & ~front before
        # relaxing and OR the improvements in after
        @jax.jit
        def without_front(act, front):
            return act & ~front

        @jax.jit
        def with_improved(act_wo, na):
            return act_wo | na

        def advance(am):
            # strict progress, exactly like the compiled path
            nb = am + delta_v
            if np.issubdtype(ldt, np.inexact):
                nb = max(nb, np.nextafter(am, np.asarray(np.inf, ldt)))
            return np.asarray(nb, ldt)

        report = []
        am, tot = (np.asarray(fetch(x)) for x in act_stats(label,
                                                           active))
        B = advance(am)
        n_adv = 0
        it = 0
        while it < iters and int(tot) > 0:
            front, cnt = front_of(label, active, jnp.asarray(B, ldt))
            cnt = int(fetch(cnt))
            if cnt == 0:
                am, tot = (np.asarray(fetch(x))
                           for x in act_stats(label, active))
                if int(tot) == 0:
                    break
                B = advance(am)
                n_adv += 1
                continue
            t = {"frontier": cnt, "bucket": float(B),
                 "advances": n_adv}
            n_adv = 0
            act_wo = without_front(active, front)
            label, na, _c = self._relax_once(label, front, cnt, t,
                                             jits, gargs)
            active = with_improved(act_wo, na)
            _am, tot = (np.asarray(fetch(x))
                        for x in act_stats(label, active))
            report.append(t)
            it += 1
        return label, active, report
