"""The push engine: frontier-driven label propagation to convergence.

The reference's push model (reference core/push_model.inl,
sssp_gpu.cu:335-522) keeps per-partition frontier queues with
dense-bitmap/sparse-queue representations, exchanges them through
zero-copy memory each iteration, pipelines SLIDING_WINDOW=4 launches,
and halts when every part's future reports an empty frontier
(sssp.cc:115-129).

The TPU-native design dissolves all of that machinery:

- The frontier is a dense boolean mask in the padded part-major vertex
  layout — a shape-stable array that all-gathers trivially over ICI
  (SURVEY.md §7 "sparse frontiers" hard part).  Inactive sources are
  masked to the reduction identity, so converged regions cost no HBM
  traffic beyond the mask read.
- The ENTIRE convergence run is one XLA program: ``lax.while_loop``
  whose predicate is a ``psum`` of active counts.  There is no
  device->host sync per iteration at all, so the reference's
  sliding-window latency-hiding trick is unnecessary by construction.
- A stepwise mode (one compiled step per call, returning the active
  count) exists for verbose per-iteration observability — the analogue
  of the reference's -verbose per-part timing (sssp_gpu.cu:516-518).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from lux_tpu.engine.program import PartCtx
from lux_tpu.graph import ShardedGraph
from lux_tpu.ops.segment import segment_reduce
from lux_tpu.ops.tiled import tiled_segment_reduce
from lux_tpu.parallel.mesh import PARTS_AXIS, parts_spec, shard_over_parts


@dataclasses.dataclass(frozen=True)
class PushProgram:
    """Monotone label-propagation program.

    reduce    'min' (SSSP/BFS) or 'max' (components) — the atomicMin/
              atomicMax of the reference's process_edge (sssp_gpu.cu:
              48-82, components_gpu.cu:57-59).
    relax     (src_label [epad], weight [epad]|None) -> candidate label
              offered to the edge's destination.
    identity  scalar no-op candidate (+inf for min, -inf/0 for max).
    init      (sharded_graph) -> (label0 [num_parts, vpad],
              active0 bool [num_parts, vpad]) numpy.
    """
    reduce: str
    relax: Callable
    identity: Any
    init: Callable

    def better(self, cand, old):
        return cand < old if self.reduce == "min" else cand > old


class PushEngine:
    """Compiled frontier iterations for one ShardedGraph + PushProgram."""

    def __init__(self, sg: ShardedGraph, program: PushProgram, mesh=None,
                 layout: str = "tiled", tile_w: int = 128,
                 tile_e: int = 512):
        if mesh is not None and sg.num_parts % mesh.devices.size != 0:
            raise ValueError(
                f"num_parts={sg.num_parts} not divisible by mesh size "
                f"{mesh.devices.size}")
        from lux_tpu.engine.pull import build_graph_arrays
        self.sg = sg
        self.program = program
        self.mesh = mesh
        arrays, self.tiles = build_graph_arrays(
            sg, layout, needs_dst=False, tile_w=tile_w, tile_e=tile_e)
        if mesh is not None:
            arrays = shard_over_parts(mesh, arrays)
        self.arrays = arrays
        self._step_fn = self._build(converge=False)
        self._converge_fn = self._build(converge=True)

    # ------------------------------------------------------------------

    def init_state(self):
        label0, active0 = self.program.init(self.sg)
        label = jnp.asarray(label0)
        active = jnp.asarray(active0)
        if self.mesh is not None:
            label = jax.device_put(label, parts_spec(self.mesh))
            active = jax.device_put(active, parts_spec(self.mesh))
        return label, active

    # -- one iteration over this device's parts ------------------------

    def _iter_parts(self, label, active, full_label, full_active, g):
        sg, prog, lay = self.sg, self.program, self.tiles
        flat_l = full_label.reshape(-1)
        flat_a = full_active.reshape(-1)

        def one(old, g):
            src_l = jnp.take(flat_l, g["src_slot"], axis=0)
            src_a = jnp.take(flat_a, g["src_slot"], axis=0)
            cand = prog.relax(src_l, g.get("weight"))
            ident = jnp.asarray(prog.identity, cand.dtype)
            cand = jnp.where(src_a, cand, ident)
            if lay is None:
                red = segment_reduce(cand, g["dst_local"], sg.vpad + 1,
                                     prog.reduce)[:sg.vpad]
            else:
                red = tiled_segment_reduce(
                    cand, lay, g["chunk_start"], g["last_chunk"],
                    g["rel_dst"], sg.vpad, prog.reduce)
            improved = prog.better(red, old) & g["vmask"]
            new = jnp.where(improved, red, old)
            return new, improved

        return jax.vmap(one)(label, g)

    # -- compiled whole-run / single-step ------------------------------

    def _build(self, converge: bool):
        keys = sorted(self.arrays)
        graph_args = tuple(self.arrays[k] for k in keys)
        on_mesh = self.mesh is not None

        def global_sum(x):
            s = jnp.sum(x)
            if on_mesh:
                s = jax.lax.psum(s, PARTS_AXIS)
            return s

        def body(label, active, g):
            if on_mesh:
                full_l = jax.lax.all_gather(label, PARTS_AXIS, tiled=True)
                full_a = jax.lax.all_gather(active, PARTS_AXIS, tiled=True)
            else:
                full_l, full_a = label, active
            new_label, new_active = self._iter_parts(
                label, active, full_l, full_a, g)
            return new_label, new_active

        def inner(label, active, max_iters, *gargs):
            g = dict(zip(keys, gargs))
            if not converge:
                new_label, new_active = body(label, active, g)
                return new_label, new_active, global_sum(new_active)

            def cond(c):
                it, lbl, act, cnt = c
                return (cnt > 0) & (it < max_iters)

            def wbody(c):
                it, lbl, act, _ = c
                nl, na = body(lbl, act, g)
                return it + 1, nl, na, global_sum(na)

            it0 = jnp.int32(0)
            cnt0 = global_sum(active)
            it, lbl, act, _ = jax.lax.while_loop(
                cond, wbody, (it0, label, active, cnt0))
            return lbl, act, it

        if on_mesh:
            P = PartitionSpec
            inner = jax.shard_map(
                inner, mesh=self.mesh,
                in_specs=(P(PARTS_AXIS), P(PARTS_AXIS), P()) +
                         (P(PARTS_AXIS),) * len(keys),
                out_specs=(P(PARTS_AXIS), P(PARTS_AXIS), P()))

        jitted = jax.jit(inner, donate_argnums=(0, 1))

        def call(label, active, max_iters=np.iinfo(np.int32).max):
            return jitted(label, active, jnp.int32(max_iters), *graph_args)

        return call

    # -- public API ----------------------------------------------------

    def step(self, label, active):
        """One compiled iteration -> (label, active, global active count
        as a device scalar)."""
        return self._step_fn(label, active)

    def converge(self, label, active, max_iters: int | None = None):
        """Run to an empty frontier inside ONE XLA program.
        Returns (label, active, iterations_executed)."""
        cap = np.iinfo(np.int32).max if max_iters is None else max_iters
        return self._converge_fn(label, active, cap)

    def run(self, max_iters: int | None = None, verbose: bool = False):
        """init -> converge -> host label array [nv]; returns
        (labels, num_iters).  verbose=True uses the stepwise path and
        prints per-iteration frontier sizes."""
        label, active = self.init_state()
        if verbose:
            it = 0
            cnt = int(jnp.sum(active)) if self.mesh is None else int(
                jax.device_get(jnp.sum(active)))
            cap = np.iinfo(np.int32).max if max_iters is None else max_iters
            while cnt > 0 and it < cap:
                label, active, c = self.step(label, active)
                cnt = int(jax.device_get(c))
                it += 1
                print(f"iter {it}: frontier={cnt}")
        else:
            label, active, it = self.converge(label, active, max_iters)
            it = int(jax.device_get(it))
        return self.unpad(label), it

    def unpad(self, state) -> np.ndarray:
        return self.sg.from_padded(np.asarray(jax.device_get(state)))
