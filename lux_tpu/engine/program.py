"""Vertex-program abstraction.

The reference specializes its two compute templates per app at compile
time through app.h typedefs + extern task hooks (reference
core/graph.h:146-225).  Here a vertex program is a small bundle of pure
functions over arrays; engines trace them under jit, so specialization
happens at XLA-compile time — the same "zero-cost per-app dispatch"
property, without separate binaries.

All functions see *padded part-local* arrays (see graph.ShardedGraph):
state ``[vpad, ...]``, per-edge values ``[epad, ...]``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable


@dataclasses.dataclass(frozen=True)
class PartCtx:
    """Per-partition context handed to program callbacks.

    deg    int32 [vpad]   out-degrees (the reference's VERTEX_DEGREE)
    vmask  bool  [vpad]   True for real (non-padding) vertex slots
    nv     int            global vertex count (static)
    ne     int            global edge count (static)
    extra  dict | None    this part's rows of the program's
                          ``extra_arrays`` (query-batch arrays like
                          personalized-PageRank reset vectors) —
                          device arrays [vpad, ...], threaded as jit
                          ARGUMENTS by the engine, never closed over
    """
    deg: Any
    vmask: Any
    nv: int
    ne: int
    extra: Any = None


def vmask_of(g, vpad: int):
    """Valid-vertex mask derived from the per-part counts ``nvp``
    graph array ([1] per part under vmap -> [vpad]; [rows, 1] stacked
    -> [rows, vpad]) — shipped as one int32 per part instead of a
    [rows, vpad] bool array (68 MB of the RMAT26 single-chip fit)."""
    import jax.numpy as jnp
    return jnp.arange(vpad, dtype=jnp.int32) < g["nvp"]


@dataclasses.dataclass(frozen=True)
class PullProgram:
    """Dense gather-apply program (the reference's pull model,
    core/pull_model.inl).

    reduce      'sum' | 'min' | 'max' — how edge messages combine per
                destination (replaces atomicAdd/Min/Max).
    edge_value  (src_val [epad,...], dst_val [epad,...], weight
                [epad]|None) -> msg [epad,...]; traced per edge batch.
    apply       (old [vpad,...], reduced [vpad,...], ctx: PartCtx) ->
                new [vpad,...]; the per-vertex epilogue (the reference's
                post-scan code, e.g. pagerank_gpu.cu:97-100).
    init        (sharded_graph) -> initial padded state
                [num_parts, vpad, ...] (numpy).
    needs_dst   whether edge_value reads dst_val (skips a gather when
                False).
    edge_value_from_dot
                optional (src_val [*,K], dot [*], weight [*]) -> msg;
                for programs whose dst dependence is ONLY through the
                inner product <src, dst> (e.g. colfilter's rating
                error).  When set and the layout is tiled, the engine
                computes the dot on the MXU from the destination TILE
                (dst values are tile-positional, so the ~9 ns/edge dst
                row-gather disappears; see PullEngine._part_step_dot).
    state_bytes bytes per VERTEX of the iterated state (itemsize x
                trailing dims), e.g. 80 for colfilter's [vpad, 20]
                f32.  Feeds resolve_exchange's state-table size
                estimate (the big-table gather cliff is in BYTES);
                None -> assume 4 (scalar f32).
    name        optional app label; engines scope their traced step
                in ``jax.named_scope(f"lux_{name}")`` so profiler
                captures (profiling.trace) attribute device ops to
                the app instead of anonymous XLA fusions.
    extra_arrays
                optional (sharded_graph) -> {name: [num_parts, vpad,
                ...] numpy} per-part constants the apply epilogue
                needs beyond deg/vmask (e.g. personalized PageRank's
                per-query reset vectors, the query-batch analogue of
                graph arrays).  The engine ships them as jit
                ARGUMENTS (key ``prog_<name>`` in its graph-array
                dict — the no-closure convention holds at any size)
                and exposes each part's row via ``ctx.extra[name]``;
                ``PullEngine.update_program_arrays`` swaps them
                in-place (same shapes, no recompile) — the serving
                front-end's continuous-batching refill path.
    batch       query-batch width B when the state carries a trailing
                query axis ``[vpad, B]`` (None = single-query).  One
                state-table gather then serves all B queries
                (machine-checked: lux_tpu/audit.py gather-budget).
    """
    reduce: str
    edge_value: Callable
    apply: Callable
    init: Callable
    needs_dst: bool = False
    edge_value_from_dot: Callable | None = None
    state_bytes: int | None = None
    name: str | None = None
    extra_arrays: Callable | None = None
    batch: int | None = None
