"""Shared scaffolding for per-iteration phase timing (the engines'
``timed_phases`` — the analogue of the reference's per-iteration
per-part loadTime/compTime/updateTime -verbose prints, reference
sssp_gpu.cu:513-518).

Each phase is a SEPARATE compiled program returning (output, scalar
fence); fetching the scalar through the tunnel is the only reliable
completion fence (CLAUDE.md).  Separate executables deliberately
prevent cross-phase fusion, so the split is honest at the cost of
materializing phase outputs and dispatch overhead — read relative
weights, not GTEPS.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp

from lux_tpu.parallel.mesh import PARTS_AXIS


def cksum(x):
    """Tiny fence value ([3] float32): depends on the phase output,
    costs nothing (the same first-8-elements convention as
    lux_tpu.timing.fence, wide-int-safe — see timing._cksum)."""
    from lux_tpu.timing import _cksum
    return _cksum(x)


def mesh_wrap(mesh, n_graph_args, parts_spec, repl_spec):
    """Returns wrap(fn, in_specs, out_spec) that shard_maps a phase fn
    over the parts mesh; the fence scalar is pmin-replicated (phase
    fns that need a true global scalar psum it themselves first —
    pmin of identical values is the identity)."""

    def wrap(fn, in_specs, out_spec):
        def inner(*a):
            out, c = fn(*a)
            return out, jax.lax.pmin(c, PARTS_AXIS)

        # check_vma off: the all-gathered flat state is value-
        # replicated but the VMA analysis cannot see it
        return jax.shard_map(
            inner, mesh=mesh, check_vma=False,
            in_specs=in_specs + (parts_spec,) * n_graph_args,
            out_specs=(out_spec, repl_spec))

    return wrap


class PhaseTimer:
    """Runs fenced phase programs, recording wall seconds per name.
    ``last_fence`` keeps the fetched fence scalar (phases may encode a
    useful global value in it, e.g. the new frontier count)."""

    def __init__(self, fetch):
        self._fetch = fetch
        self.t = {}
        self.last_fence = None

    def __call__(self, name, fn, *args):
        from lux_tpu.profiling import annotation
        with annotation(f"lux_phase_{name}"):
            t0 = time.perf_counter()
            out, c = fn(*args)
            self.last_fence = self._fetch(c)
            self.t[name] = time.perf_counter() - t0
        return out
