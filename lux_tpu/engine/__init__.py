from lux_tpu.engine.program import PartCtx, PullProgram
from lux_tpu.engine.pull import PullEngine
