"""The pull engine: dense gather-apply iterations.

One iteration (the analogue of one PullAppTask index launch,
reference pull_model.inl:423-470 + pagerank_gpu.cu:104-151):

1. make the full vertex state visible to every part — single device:
   a reshape; mesh: ``lax.all_gather`` over the ``parts`` axis (the
   reference's whole-region READ_ONLY requirement that Legion/GASNet
   materialize remotely, pull_model.inl:454-461);
2. gather each edge's source state by precomputed padded slot;
3. per-edge message (program.edge_value);
4. scatter-free segment reduction to each part's local destinations
   (replacing the CUB BlockScan + atomicAdd CTA pattern, SURVEY.md
   §3.3) — by default via the tiled chunk layout (ops/tiled.py),
   which keeps the hot loop on dense VPU/MXU ops; ``layout="flat"``
   falls back to the XLA scatter path (ops/segment.py), the
   correctness oracle;
5. per-vertex apply epilogue.

Fixed-iteration runs are fused into a single XLA program with
``lax.fori_loop`` — the TPU-native version of the reference's
fire-and-forget launch pipeline (pagerank.cc:109-114), with zero host
round-trips instead of deferred-execution tricks.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from lux_tpu.engine.auditable import AuditableEngine
from lux_tpu.engine.program import PartCtx, PullProgram, vmask_of
from lux_tpu.graph import ShardedGraph
from lux_tpu.ops.segment import segment_reduce
from lux_tpu.ops.tiled import (STREAM_MSG_BYTES, TiledLayout,
                               combine_chunks, combine_op,
                               tiled_segment_reduce)
from lux_tpu.parallel.mesh import PARTS_AXIS, shard_over_parts


# chunks per lax.map block in the dot path: bounds the [B, E, W]
# intermediate (~32 MB at the default tile sizes; 128 measured best
# on v5e, within 3% of every size from 32 up)
DOT_BLOCK_CHUNKS = 128


def _dot_kdim(program) -> int:
    """K of a dot-path program's vector state — feeds the K-aware pair
    economics (min_fill="auto", ops/pairs.resolve_min_fill) and the
    SDDMM streaming budget.  Programs using edge_value_from_dot should
    set state_bytes = 4 * K (colfilter does); unset falls back to
    scalar economics."""
    if getattr(program, "edge_value_from_dot", None) is None:
        return 1
    sb = getattr(program, "state_bytes", None)
    return max(1, (sb or 4) // 4)



def resolve_reduce_method(method: str) -> str:
    """'auto' picks the Pallas kernel on real TPUs and the portable
    XLA formulation elsewhere (including the CPU test mesh);
    'pallas-interpret' forces the kernel in interpreter mode so its
    code path is testable off-TPU."""
    if method == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    if method in ("xla", "pallas", "pallas-interpret"):
        return method
    raise ValueError(f"unknown reduce_method {method!r}")


# auto exchange: go owner-side once the flat state table passes this
# many bytes — the measured XLA gather emitter step sits at ~64-128 MB
# (scripts/profile_bigtable.py), so 96 MB splits the band; below it the
# owner layout's chunk padding isn't worth carrying
OWNER_AUTO_BYTES = 96 << 20


def resolve_exchange(exchange: str, sg: ShardedGraph, program,
                     itemsize: int | None = None) -> str:
    """'auto' picks 'owner' when the program qualifies (source-only
    edge values; full AND multi-host local-parts builds both qualify)
    and the state table would pay the big-table gather tax; 'gather'
    otherwise.

    itemsize: bytes per VERTEX for the table estimate (itemsize x
    trailing dims).  Default: the program's ``state_bytes`` (pull) or
    its ``identity`` dtype's itemsize (push); 4 when neither exists."""
    if exchange == "auto":
        if itemsize is None:
            itemsize = getattr(program, "state_bytes", None)
        if itemsize is None:
            ident = getattr(program, "identity", None)
            itemsize = (np.asarray(ident).dtype.itemsize
                        if ident is not None else 4)
        # works for Pull AND Push programs (push has no dst/dot hooks)
        eligible = (not getattr(program, "needs_dst", False)
                    and getattr(program, "edge_value_from_dot",
                                None) is None)
        big = sg.num_parts * sg.vpad * itemsize > OWNER_AUTO_BYTES
        return "owner" if (eligible and big) else "gather"
    if exchange not in ("gather", "owner"):
        raise ValueError(f"unknown exchange {exchange!r}")
    return exchange


def mxu_wide_of(program) -> int:
    """K x B payload width of a program's state — the free MXU minor
    dimension the round-23 one-hot reduce amortizes its toll over
    (scalemodel.mxu_break_even_wide).  K from state_bytes (itemsize x
    trailing dims, the _dot_kdim convention), B from the query batch;
    both multiply."""
    sb = getattr(program, "state_bytes", None)
    if sb is not None:
        # state_bytes covers the FULL trailing row — colfilter's 4*K,
        # batched pagerank's itemsize*B — so it already is K x B
        return max(1, sb // 4)
    return int(getattr(program, "batch", None) or 1)


def resolve_use_mxu(use_mxu, program) -> bool:
    """``use_mxu="auto"`` (engine default) engages the MXU one-hot
    reduce when the program's K x B payload width amortizes the
    one-hot materialization toll (scalemodel.resolve_use_mxu: sum
    engages at width >= 2 — ppr's B=8 batch and colfilter's K=20 do,
    scalar f32 flagships stay on the fused VPU path bit-for-bit;
    min/max never auto-engage, the tournament is for the measured
    A/B).  True/False force the path for A/B benches and tests."""
    if isinstance(use_mxu, bool):
        return use_mxu
    if use_mxu != "auto":
        raise ValueError(f"unknown use_mxu {use_mxu!r}")
    from lux_tpu import scalemodel
    kind = getattr(program, "reduce", "sum")
    return scalemodel.resolve_use_mxu(kind, mxu_wide_of(program))


def common_graph_arrays(sg: ShardedGraph, dev):
    """deg + nvp, the apply-epilogue arrays every layout needs.  The
    valid-vertex mask is DERIVED on device from the per-part counts
    (iota < nvp, see program.vmask_of's [rows, 1] int32 convention)
    instead of shipping a [rows, vpad] bool array — 68 MB of the
    RMAT26 single-chip fit (PERF_NOTES)."""
    return dict(deg=dev(sg.deg_padded),
                nvp=dev(sg.nv_part[sg.part_ids()].astype(
                    np.int32)[:, None]))


def _owner_edge_arrays(owner, dev):
    """The owner layout's per-slot arrays: packed (uint32 src<<7|rel
    + uint16 live-lane counts) or classic (int32 src + int8 rel) —
    see ops/owner.OwnerLayout's packed encoding note."""
    if owner.packed:
        return dict(own_sr=dev(owner.src_rel),
                    own_nv=dev(owner.n_valid))
    return dict(own_src=dev(owner.src_local),
                own_rel=dev(owner.rel_dst))


def build_graph_arrays(sg: ShardedGraph, layout: str, needs_dst: bool,
                       tile_w: int, tile_e: int, device: bool = True):
    """Per-part graph arrays (all leading dim num_parts) for either
    edge layout; returns (arrays dict, TiledLayout|None).

    device=False keeps them as host numpy — mesh engines place them
    with ``shard_over_parts`` directly (one H2D per shard), instead of
    staging everything through the default device first."""
    dev = jnp.asarray if device else np.asarray
    common = common_graph_arrays(sg, dev)
    if layout == "flat":
        arrays = dict(src_slot=dev(sg.src_slot),
                      dst_local=dev(sg.dst_local), **common)
        if sg.weighted:
            arrays["weight"] = dev(sg.edge_weight)
        return arrays, None
    if layout != "tiled":
        raise ValueError(f"unknown layout {layout!r}")
    lay = TiledLayout.build(
        sg.row_ptr_local, sg.dst_local, sg.vpad, W=tile_w, E=tile_e,
        sizing_row_ptr=(None if sg.local_parts is None
                        else sg.sizing_row_ptr()))
    arrays = dict(src_slot=dev(lay.chunk(sg.src_slot)),
                  rel_dst=dev(lay.rel_dst),
                  chunk_start=dev(lay.chunk_start),
                  last_chunk=dev(lay.last_chunk), **common)
    if sg.weighted:
        arrays["weight"] = dev(lay.chunk(sg.edge_weight))
    if needs_dst:
        arrays["chunk_tile"] = dev(lay.chunk_tile)
    return arrays, lay


class PullEngine(AuditableEngine):
    """Compiled pull-model iterations for one ShardedGraph + program.

    With ``mesh=None`` everything runs on one device (parts stacked on
    the leading axis, vmapped).  With a mesh, all part-major arrays are
    sharded over the ``parts`` axis and the same per-part computation
    runs under shard_map with an all-gather for remote state.
    """

    def __init__(self, sg: ShardedGraph, program: PullProgram, mesh=None,
                 layout: str = "tiled", tile_w: int = 128,
                 tile_e: int = 512, use_mxu: bool | str = "auto",
                 reduce_method: str = "auto",
                 pair_threshold: int | None = None,
                 pair_min_fill: int | str | None = None,
                 pair_stream: bool | None = None,
                 stream_msgs: bool | None = None,
                 exchange: str = "auto",
                 gather: str = "flat",
                 owner_tile_e: int | None = None,
                 owner_minmax_fused: bool = False,
                 stats_cap: int | None = None,
                 health: bool = False,
                 audit: str | None = None):
        if mesh is not None and sg.num_parts % mesh.devices.size != 0:
            raise ValueError(
                f"num_parts={sg.num_parts} not divisible by mesh size "
                f"{mesh.devices.size}")
        exchange = resolve_exchange(exchange, sg, program)
        if exchange == "owner" and (
                program.needs_dst
                or program.edge_value_from_dot is not None):
            raise ValueError(
                "exchange='owner' supports programs whose edge_value "
                "depends only on the source state (owner-side parts "
                "hold no destination state)")
        _check_local_parts(sg, mesh, pair_threshold)
        self.exchange = exchange
        # psum_scatter-style fused min/max owner exchange (ring
        # reduce-scatter, ops/owner.py) — opt-in until measured on a
        # real mesh
        self.owner_minmax_fused = bool(owner_minmax_fused)
        self.pairs = None
        # paged two-level gather (ops/pagegather.py): replaces the
        # per-edge state-table gather with a page-binned row fetch +
        # Pallas lane shuffle; an alternative row-delivery layout to
        # the pair plan, so the two never compose
        self.page_plan = None
        self.gather = "flat"
        if gather != "flat":
            if gather in ("paged", "pagemajor") \
                    and pair_threshold is not None:
                raise ValueError(
                    f"gather={gather!r} subsumes pair delivery (both "
                    f"are row-granular layouts); build without "
                    f"pair_threshold")
            if pair_threshold is None:
                self._setup_paged(sg, gather, program, exchange)
        if pair_threshold is not None:
            sg = self._setup_pairs(sg, pair_threshold, mesh, layout,
                                   program, pair_min_fill)
        from lux_tpu.ops.pairs import (resolve_pair_dot_stream,
                                       resolve_pair_stream)
        self.pair_stream = resolve_pair_stream(pair_stream, self.pairs)
        # the SDDMM (K-dim) pair path streams by the shared 1 GB
        # budget (ops/tiled.STREAM_MSG_BYTES) instead of always: under
        # it the monolithic lax.map measured best; past it the stacked
        # per-row partials are the 67.7 GB NetFlix compile allocation
        self.pair_dot_stream = resolve_pair_dot_stream(
            pair_stream, self.pairs, len(sg.part_ids()),
            _dot_kdim(program))
        # auto: stream once the [rows, C, E] f32 message temporary
        # passes the budget — vmap materializes EVERY materialized
        # part's messages together (sg here is the pair residual when
        # pairs are on; mesh devices hold rows/ndev of this, so the
        # estimate is conservative there)
        rows = len(sg.part_ids())
        self.stream_chunks = (rows * sg.epad * 4 > STREAM_MSG_BYTES
                              if stream_msgs is None
                              else bool(stream_msgs))
        if program.edge_value_from_dot is not None:
            if program.reduce != "sum":
                raise ValueError(
                    "edge_value_from_dot requires reduce='sum' (the "
                    "mask-matmul partial reduction is a sum)")
            if not sg.weighted:
                raise ValueError(
                    "edge_value_from_dot requires a weighted graph "
                    "(the dot path passes per-edge weights)")
        self.sg = sg
        self.program = program
        self.mesh = mesh
        self.use_mxu = resolve_use_mxu(use_mxu, program)
        # health=True: run()/segmented drivers use the watchdog loop
        # variants (run_health / run_until_health, compiled lazily);
        # False leaves every watchdog-free program untouched
        self.health = bool(health)
        from lux_tpu.telemetry import DEFAULT_STATS_CAP
        self.stats_cap = int(stats_cap or DEFAULT_STATS_CAP)
        self.reduce_method = resolve_reduce_method(reduce_method)
        dev = jnp.asarray if mesh is None else np.asarray
        if self.page_plan is not None:
            # the paged plan IS the edge layout: neither the tiled
            # chunk arrays nor the owner chunk layout is built
            self.owner = None
            self.tiles = None
            arrays = dict(common_graph_arrays(sg, dev),
                          **self._paged_arrays(dev, program))
        elif exchange == "owner":
            from lux_tpu.ops.owner import OwnerLayout
            self.owner = OwnerLayout.build(sg, E=owner_tile_e or 256)
            self.tiles = None
            arrays = dict(
                **common_graph_arrays(sg, dev),
                **_owner_edge_arrays(self.owner, dev),
                own_cs=dev(self.owner.chunk_start),
                own_lc=dev(self.owner.last_chunk))
            if self.owner.weight is not None:
                arrays["own_w"] = dev(self.owner.weight)
            if self.owner.streams():
                # fused streamed combine: never materializes [C, W]
                ep, et = self.owner.extract_plan()
                arrays["own_ep"] = dev(ep)
                arrays["own_et"] = dev(et)
        else:
            self.owner = None
            arrays, self.tiles = build_graph_arrays(
                sg, layout,
                program.needs_dst
                or program.edge_value_from_dot is not None,
                tile_w, tile_e, device=mesh is None)
        if program.extra_arrays is not None:
            # program-contributed per-part constants (e.g. per-query
            # reset vectors): jit ARGUMENTS like every graph array —
            # the no-closure convention holds for query state too
            for k, v in program.extra_arrays(sg).items():
                arrays[f"prog_{k}"] = dev(np.asarray(v))
        if self.pairs is not None:
            arrays["pair_rowbind"] = dev(self.pairs.rowbind)
            arrays["pair_rel"] = dev(self.pairs.rel_dst)
            arrays["pair_tile_pos"] = dev(self.pairs.tile_pos)
            if self.pairs.weight is not None:
                arrays["pair_weight"] = dev(self.pairs.weight)
            if program.edge_value_from_dot is not None:
                # the SDDMM pair path also fetches each row's dst tile
                arrays["pair_row_tile"] = dev(self.pairs.row_tile)
                arrays["pair_tile0"] = dev(
                    (np.arange(sg.num_parts) *
                     (sg.vpad // 128)).astype(np.int32)[:, None])
        if mesh is not None:
            arrays = shard_over_parts(mesh, arrays, sg.num_parts)
        self.arrays = arrays
        # compiled-variant registry for the static program auditor
        # (lux_tpu/audit.py): name -> (jitted fn, example-args thunk)
        self._audit_variants: dict = {}
        self._step_fn = self._build_step()
        if audit is not None:
            # mode validation lives in audit_engine (typed ValueError
            # on anything but 'warn'/'error')
            from lux_tpu import audit as _audit
            _audit.audit_engine(self, mode=audit)

    # -- pair-lane fast path (ops/pairs.py) ----------------------------

    def _setup_pairs(self, sg: ShardedGraph, threshold: int, mesh,
                     layout, program, min_fill=None):
        """Split dense (src-tile, dst-tile) pair edges out of the
        regular gather path (see ops/pairs.py): gather cost is per ROW
        fetched, so pair rows fetch a 128-wide source state row once
        and deliver positionally.  Works for any num_parts, with or
        without a mesh, and on weighted graphs (per-lane weights).
        Returns the RESIDUAL ShardedGraph the normal machinery should
        run on."""
        from lux_tpu.ops.pairs import plan_sharded_pairs

        if layout != "tiled":
            raise ValueError("pair_threshold requires the tiled layout")
        if getattr(program, "batch", None) is not None:
            raise ValueError(
                "pair_threshold does not support query-batched "
                "programs: pair delivery reads scalar vertex state "
                "(ops/pairs.pair_partial); run batched engines "
                "without pairs")
        if program.needs_dst and program.edge_value_from_dot is None:
            raise ValueError("pair_threshold supports programs whose "
                             "edge_value depends only on the source "
                             "state, or on <src, dst> via "
                             "edge_value_from_dot")
        sp, residual = plan_sharded_pairs(sg, threshold,
                                          min_fill=min_fill,
                                          kdim=_dot_kdim(program))
        self.pairs = sp                      # None if nothing dense
        return residual

    def _pair_red(self, flat_state, g):
        """Pair-lane delivery + reduce for one part -> [vpad] partial
        (identity where pairs contribute nothing)."""
        from lux_tpu.ops.pairs import pair_partial, pair_partial_streamed

        prog = self.program
        fn = pair_partial_streamed if self.pair_stream else pair_partial
        red = fn(
            self.pairs, flat_state, g["pair_rowbind"], g["pair_rel"],
            g.get("pair_weight"), g["pair_tile_pos"], prog.reduce,
            lambda vals, w: prog.edge_value(vals, None, w),
            reduce_method=self.reduce_method)
        return red[:self.sg.vpad]

    # -- paged two-level gather (ops/pagegather.py) --------------------

    def _setup_paged(self, sg: ShardedGraph, gather: str, program,
                     exchange: str):
        """Build the page-binned delivery plan and resolve
        ``gather="auto"`` by the scalemodel break-even on its MEASURED
        unique-page ratio / row fill (scalemodel.page_gather_ns) —
        ops/pagegather.engine_page_plan holds the shared rule."""
        from lux_tpu.ops.pagegather import engine_page_plan

        self.page_plan = engine_page_plan(sg, gather, program, exchange)
        if self.page_plan is not None:
            self.gather = self.page_plan.mode

    def _paged_arrays(self, dev, program):
        """The paged plan's graph arrays
        (ops/pagegather.plan_graph_arrays)."""
        from lux_tpu.ops.pagegather import plan_graph_arrays
        return plan_graph_arrays(
            self.page_plan, dev, owner=self.exchange == "owner",
            dot=getattr(program, "edge_value_from_dot", None)
            is not None,
            num_parts=self.sg.num_parts, vpad=self.sg.vpad)

    def _paged_red(self, flat_state, g):
        """Paged delivery + reduce for one part -> [vpad, ...] (total
        coverage: the plan serves EVERY edge, no residual).  The
        page-major plan rides the same call: ``pg_vrs`` binds each
        virtual reduce row to its full-fill gather row
        (ops/pagegather.PagedPlan mode="pagemajor")."""
        from lux_tpu.ops.pagegather import paged_partial

        prog = self.program
        red = paged_partial(
            self.page_plan, flat_state, g["pg_ids"], g["pg_sl"],
            g["pg_rel"], g.get("pg_w"), g["pg_tp"], prog.reduce,
            lambda vals, w: prog.edge_value(vals, None, w),
            reduce_method=self.reduce_method,
            vrow_src=g.get("pg_vrs"))
        return red[:self.sg.vpad]

    def _paged_dot_red(self, flat_state, g):
        """Paged SDDMM delivery (ops/pagegather.paged_partial_dot) —
        pair_partial_dot's MXU pipeline plus the one-hot lane-shuffle
        contraction."""
        from lux_tpu.ops.pagegather import paged_partial_dot

        red = paged_partial_dot(
            self.page_plan, flat_state, g["pg_ids"], g["pg_sl"],
            g["pg_rel"], g["pg_w"], g["pg_rt"], g["pg_tp"],
            g["pg_t0"][0], self.program.edge_value_from_dot)
        return red[:self.sg.vpad]

    def _part_step_paged(self, flat_state, old_p, g):
        with jax.named_scope("lux_gather_reduce"):
            red = self._paged_red(flat_state, g)
        with jax.named_scope("lux_apply"):
            return self._apply_epilogue(old_p, red, g)

    def _part_step_paged_dot(self, flat_state, old_p, g):
        with jax.named_scope("lux_dot_reduce"):
            red = self._paged_dot_red(flat_state, g)
        with jax.named_scope("lux_apply"):
            return self._apply_epilogue(old_p, red, g)

    # -- state placement ----------------------------------------------

    def init_state(self):
        state = self._consume_pending_init()
        if state is None:
            state = self.program.init(self.sg)
        if self.mesh is not None:
            return shard_over_parts(self.mesh, [np.asarray(state)],
                                    self.sg.num_parts)[0]
        return jnp.asarray(state)

    def place(self, state):
        """Put a host state pytree on the engine's devices with the
        parts sharding (mirrors init_state's placement; used by
        checkpoint/resilience resume).  This is also the elastic
        RE-PLACEMENT entry point (round 11): the input is the global
        ``[P, vpad, ...]`` view, so the same call re-shards a
        checkpoint written on an 8-device mesh onto this engine's
        4-device one — parts fixed, device mapping changed."""
        self._drop_pending_init()     # resume never needs the probe
        leaves, treedef = jax.tree.flatten(state)
        if self.mesh is not None:
            leaves = shard_over_parts(
                self.mesh, [np.asarray(x) for x in leaves],
                self.sg.num_parts)
        else:
            leaves = [jnp.asarray(x) for x in leaves]
        return jax.tree.unflatten(treedef, leaves)

    def update_program_arrays(self, **host_arrays):
        """Swap program-contributed per-part arrays
        (``PullProgram.extra_arrays``; key ``<name>`` here maps to
        graph-array key ``prog_<name>``) with SAME-shape/dtype host
        replacements — no recompile: every compiled variant reads
        ``self.graph_args`` at call time, so the next step/run sees
        the new arrays.  This is the serving front-end's
        continuous-batching refill path (lux_tpu/serve.py): a retired
        query column's reset vector is replaced without rebuilding
        the engine."""
        for k, v in host_arrays.items():
            key = f"prog_{k}"
            if key not in self.arrays:
                raise KeyError(
                    f"engine has no program array {k!r} "
                    f"(program.extra_arrays supplies "
                    f"{[x[5:] for x in self.arrays if x.startswith('prog_')]})")
            cur = self.arrays[key]
            arr = np.asarray(v)
            if (arr.shape != tuple(cur.shape)
                    or np.dtype(arr.dtype) != np.dtype(cur.dtype)):
                raise ValueError(
                    f"program array {k!r} must keep shape "
                    f"{tuple(cur.shape)}/{np.dtype(cur.dtype)} "
                    f"(got {arr.shape}/{arr.dtype}) — shapes are "
                    f"compiled; rebuild the engine to change B")
            if self.mesh is not None:
                arr = shard_over_parts(self.mesh, [arr],
                                       self.sg.num_parts)[0]
            else:
                arr = jnp.asarray(arr)
            self.arrays[key] = arr
        self.graph_args = tuple(self.arrays[k] for k in self._graph_keys)

    # -- one part's work ----------------------------------------------

    def _apply_epilogue(self, old_p, red, g):
        sg, prog = self.sg, self.program
        vm = vmask_of(g, sg.vpad)
        extra = {k[5:]: g[k] for k in g if k.startswith("prog_")}
        ctx = PartCtx(deg=g["deg"], vmask=vm, nv=sg.nv, ne=sg.ne,
                      extra=extra or None)
        new = prog.apply(old_p, red, ctx)
        keep = vm.reshape(vm.shape + (1,) * (new.ndim - 1))
        return jnp.where(keep, new, old_p)

    def _part_msgs(self, flat_state, old_p, g):
        """Phase 1 (gather): per-edge source gather + message values."""
        prog, sg, lay = self.program, self.sg, self.tiles
        src_vals = jnp.take(flat_state, g["src_slot"], axis=0)
        if prog.needs_dst:
            if lay is None:
                dst_idx = jnp.minimum(g["dst_local"], sg.vpad - 1)
            else:
                # pad lanes carry rel -1 (int8 marker): clip keeps the
                # garbage gather in range; the reduce masks it anyway
                dst_idx = jnp.clip(
                    g["chunk_tile"][:, None] * lay.W + g["rel_dst"],
                    0, sg.vpad - 1)
            dst_vals = jnp.take(old_p, dst_idx, axis=0)
        else:
            dst_vals = None
        msgs = prog.edge_value(src_vals, dst_vals, g.get("weight"))
        if lay is not None and (self.reduce_method == "xla"
                                or msgs.ndim != 2):
            # Keep the (serial, expensive) gather from being fused
            # into the W-wide broadcast consumer, which re-executes
            # it per output lane — measured 3-5x slower on v5e.
            # The Pallas kernel is an opaque boundary and needs no
            # barrier.
            msgs = jax.lax.optimization_barrier(msgs)
        return msgs

    def _part_reduce(self, flat_state, msgs, g):
        """Phase 2 (reduce): scatter-free segment reduction (+ the
        pair-lane delivery, which fetches and reduces in one go)."""
        prog, sg, lay = self.program, self.sg, self.tiles
        if lay is None:
            red = segment_reduce(msgs, g["dst_local"], sg.vpad + 1,
                                 prog.reduce)[:sg.vpad]
        else:
            red = tiled_segment_reduce(
                msgs, lay, g["chunk_start"], g["last_chunk"],
                g["rel_dst"], sg.vpad, prog.reduce, use_mxu=self.use_mxu,
                method=("xla" if msgs.ndim != 2 else
                        "pallas" if self.reduce_method.startswith("pallas")
                        else "xla"),
                interpret=self.reduce_method == "pallas-interpret")
        return self._combine_pairs(flat_state, red, g)

    def _combine_pairs(self, flat_state, red, g):
        if self.pairs is not None:
            red = combine_op(self.program.reduce)(
                red, self._pair_red(flat_state, g))
        return red

    @property
    def _streams(self) -> bool:
        return (self.stream_chunks and self.tiles is not None
                and not self.program.needs_dst)

    def _part_red_streamed(self, flat_state, g):
        """Gather + message + partials in chunk blocks (ops/tiled.
        streamed_chunk_partials), combined to [vpad] with the pair
        contribution — the billion-edge form of gather+reduce."""
        from lux_tpu.ops.tiled import (combine_partials,
                                       streamed_chunk_partials)
        prog, sg, lay = self.program, self.sg, self.tiles
        partials = streamed_chunk_partials(
            flat_state, g["src_slot"], g["rel_dst"], g.get("weight"),
            lay, prog.reduce,
            lambda vals, w: prog.edge_value(vals, None, w),
            self.reduce_method, use_mxu=self.use_mxu)
        red = combine_partials(partials, lay, g["chunk_start"],
                               g["last_chunk"], sg.vpad, prog.reduce,
                               use_mxu=self.use_mxu)
        return self._combine_pairs(flat_state, red, g)

    def _part_step(self, flat_state, old_p, g):
        """g: dict of this part's graph arrays."""
        if self._streams:
            with jax.named_scope("lux_gather_reduce"):
                red = self._part_red_streamed(flat_state, g)
            with jax.named_scope("lux_apply"):
                return self._apply_epilogue(old_p, red, g)
        with jax.named_scope("lux_gather"):
            msgs = self._part_msgs(flat_state, old_p, g)
        with jax.named_scope("lux_reduce"):
            red = self._part_reduce(flat_state, msgs, g)
        with jax.named_scope("lux_apply"):
            return self._apply_epilogue(old_p, red, g)

    def _part_step_dot(self, flat_state, old_p, g):
        red = self._part_dot_red(flat_state, old_p, g)
        return self._apply_epilogue(old_p, red, g)

    def _part_dot_red(self, flat_state, old_p, g):
        """Tiled-layout reduction for programs whose dst dependence is
        only the inner product <src, dst> (program.edge_value_from_dot).

        The dst row-gather (~9 ns/edge, 75% of a colfilter iteration)
        is replaced by MXU matmuls against the chunk's destination
        TILE: per chunk, D = src @ tile^T gives every (edge, dst-lane)
        dot; a lane-compare selects each edge's own dot; and the
        message reduction is a one-hot mask matmul — the SGD gradient
        as two batched matmuls (the TPU answer to the reference's
        shared-memory gradient staging, colfilter_gpu.cu:41-102).
        Chunks are processed in lax.map blocks so the [B, E, W]
        intermediates stay small.
        """
        sg, lay, prog = self.sg, self.tiles, self.program
        W, E = lay.W, lay.E
        C = lay.n_chunks
        Kdim = old_p.shape[-1]

        n_tiles = lay.n_tiles
        old_pad = jnp.pad(old_p, ((0, n_tiles * W - sg.vpad), (0, 0)))
        tiles = old_pad.reshape(n_tiles, W, Kdim)
        rel = g["rel_dst"]
        wgt = g.get("weight")

        B = max(1, min(DOT_BLOCK_CHUNKS, C))
        nB = (C + B - 1) // B
        Cp = nB * B

        def pad_c(x):
            return jnp.pad(x, ((0, Cp - C),) + ((0, 0),) * (x.ndim - 1))

        lanes = jnp.arange(W, dtype=rel.dtype)

        def block(args):
            # BOTH gathers happen per block: materializing the [C, E,
            # K] source values / [C, W, K] tile rows whole-graph is
            # ~15 GB at the NetFlix shape (measured OOM, round 5) —
            # the block bound must cover the gather outputs, not just
            # the [B, E, W] dot intermediate
            slot_b, ct_b, r, w = args
            s = jnp.take(flat_state, slot_b, axis=0)       # [B, E, K]
            s = jax.lax.optimization_barrier(s)
            t = jnp.take(tiles, jnp.minimum(ct_b, n_tiles - 1),
                         axis=0)                           # [B, W, K]
            D = jnp.einsum("bek,bwk->bew", s, t,
                           preferred_element_type=s.dtype)
            mask = r[..., None] == lanes                   # [B, E, W]
            dot = jnp.sum(jnp.where(mask, D, 0), axis=-1)  # [B, E]
            msgs = prog.edge_value_from_dot(s, dot, w)     # [B, E, K]
            return jnp.einsum("bew,bek->bwk", mask.astype(s.dtype),
                              msgs)                        # [B, W, K]

        args = (pad_c(g["src_slot"]).reshape(nB, B, E),
                pad_c(g["chunk_tile"]).reshape(nB, B),
                pad_c(rel).reshape(nB, B, E),
                pad_c(wgt).reshape(nB, B, E))
        partials = jax.lax.map(block, args).reshape(Cp, W, Kdim)[:C]
        red = combine_chunks(partials, lay, g["chunk_start"],
                             g["last_chunk"], prog.reduce,
                             use_mxu=self.use_mxu)
        red = red.reshape(n_tiles * W, Kdim)[:sg.vpad]
        if self.pairs is not None:
            from lux_tpu.ops.pairs import (pair_partial_dot,
                                           pair_partial_dot_streamed)
            fn = (pair_partial_dot_streamed if self.pair_dot_stream
                  else pair_partial_dot)
            pred = fn(
                self.pairs, flat_state, g["pair_rowbind"],
                g["pair_rel"], g["pair_weight"], g["pair_row_tile"],
                g["pair_tile_pos"], g["pair_tile0"][0],
                prog.edge_value_from_dot)
            red = red + pred[:sg.vpad]
        return red

    def _parts_step(self, local_state, full_state, g_local):
        """vmap _part_step over this device's parts."""
        sg = self.sg
        flat = full_state.reshape((sg.num_parts * sg.vpad,) +
                                  full_state.shape[2:])
        use_dot = self.program.edge_value_from_dot is not None
        if self.page_plan is not None:
            step = (self._part_step_paged_dot if use_dot
                    else self._part_step_paged)
        else:
            step = (self._part_step_dot
                    if use_dot and self.tiles is not None
                    else self._part_step)
        return jax.vmap(lambda old, g: step(flat, old, g))(
            local_state, g_local)

    # -- owner-side exchange (ops/owner.py) ---------------------------

    def _msg_dtype(self, state):
        """Message dtype without running edge_value (abstract eval)."""
        probe_w = (jax.ShapeDtypeStruct((1, 1), jnp.float32)
                   if self.sg.weighted else None)
        probe_s = jax.ShapeDtypeStruct((1, 1) + state.shape[2:],
                                       state.dtype)
        return jax.eval_shape(
            lambda s, w: self.program.edge_value(s, None, w),
            probe_s, probe_w).dtype

    def _owner_contribs(self, state_rows, g):
        """Per-source-part contributions (ops/owner.owner_contribs;
        paged engines run the page-binned shard delivery under the
        same generation scan, ops/pagegather.paged_owner_contribs)."""
        prog = self.program
        if self.page_plan is not None:
            from lux_tpu.ops.pagegather import paged_owner_contribs
            return paged_owner_contribs(
                self.page_plan, state_rows, g, prog.reduce,
                lambda vals, wt: prog.edge_value(vals, None, wt),
                self._msg_dtype(state_rows), self.sg.num_parts,
                self.reduce_method,
                varying_axis=None if self.mesh is None else PARTS_AXIS)
        from lux_tpu.ops.owner import owner_contribs

        return owner_contribs(
            self.owner, state_rows, g,
            prog.reduce,
            lambda vals, wt: prog.edge_value(vals, None, wt),
            self._msg_dtype(state_rows), self.sg.num_parts,
            self.reduce_method,
            varying_axis=None if self.mesh is None else PARTS_AXIS,
            use_mxu=self.use_mxu)

    def _owner_exchange(self, acc):
        """Reduce-scatter of contributions (ops/owner.owner_exchange)."""
        from lux_tpu.ops.owner import owner_exchange

        return owner_exchange(
            acc, self.program.reduce,
            axis=None if self.mesh is None else PARTS_AXIS,
            ndev=1 if self.mesh is None else self.mesh.devices.size,
            minmax_fused=self.owner_minmax_fused)

    def _owner_apply(self, state_rows, red_rows, flat_state, g):
        """Pair contribution + apply epilogue, vmapped over the local
        destination parts.  flat_state (full [P*vpad, ...] table) is
        None when no pair delivery needs it."""

        def per_part(old_p, red_p, gp):
            if flat_state is not None:
                red_p = self._combine_pairs(flat_state, red_p, gp)
            return self._apply_epilogue(old_p, red_p, gp)

        return jax.vmap(per_part)(state_rows, red_rows, g)

    def _owner_step(self, state, g):
        """One owner-exchange iteration for the locally-held rows
        (single device: all parts; under shard_map: this device's)."""
        sg = self.sg
        with jax.named_scope("lux_gen_exchange"):
            if (self.page_plan is not None
                    and self.page_plan.mode == "pagemajor"):
                # page-major routing: full message rows all_to_all to
                # their destination parts, reduced receiver-side — no
                # per-tile partials, no separate owner exchange
                # (ops/pagegather.pagemajor_owner_deliver)
                from lux_tpu.ops.pagegather import \
                    pagemajor_owner_deliver
                prog = self.program
                red = pagemajor_owner_deliver(
                    self.page_plan, state, g, prog.reduce,
                    lambda vals, wt: prog.edge_value(vals, None, wt),
                    self._msg_dtype(state), sg.num_parts,
                    self.reduce_method,
                    axis=None if self.mesh is None else PARTS_AXIS,
                    varying_axis=(None if self.mesh is None
                                  else PARTS_AXIS))[:, :sg.vpad]
                return self._owner_apply(state, red, None, g)
            acc = self._owner_contribs(state, g)
            red = self._owner_exchange(acc)[:, :sg.vpad]
        flat = None
        if self.pairs is not None:
            # pair rows are fetched from the FULL table (row-granular
            # fetches, not subject to the element-gather big-table
            # tax); on the mesh the all_gather exists only for them
            full = (state if self.mesh is None else
                    jax.lax.all_gather(state, PARTS_AXIS, tiled=True))
            flat = full.reshape((sg.num_parts * sg.vpad,) +
                                full.shape[2:])
        return self._owner_apply(state, red, flat, g)

    # -- full step over all parts -------------------------------------

    def _build_step(self):
        """Builds self._graph_args and the un-jitted core
        step(state, *graph_args); returns a jitted single-step wrapper.

        Graph arrays are always passed as ARGUMENTS, never closed over:
        closing over them would bake hundreds of MB of edge indices
        into the XLA program as constants.
        """
        keys = sorted(self.arrays)
        self._graph_keys = keys
        self.graph_args = tuple(self.arrays[k] for k in keys)

        if self.exchange == "owner":
            if self.mesh is None:
                def core(state, *gargs):
                    return self._owner_step(state,
                                            dict(zip(keys, gargs)))
            else:
                P = PartitionSpec

                @functools.partial(
                    jax.shard_map, mesh=self.mesh,
                    in_specs=(P(PARTS_AXIS),) * (1 + len(keys)),
                    out_specs=P(PARTS_AXIS))
                def core(state, *gargs):
                    return self._owner_step(state,
                                            dict(zip(keys, gargs)))

            if self.program.name:
                core = jax.named_scope(
                    f"lux_{self.program.name}")(core)
            self._step_core = core
            jitted = jax.jit(core, donate_argnums=0)
            self._register_variant(
                "step", jitted,
                lambda: (self._audit_state_sds, *self.graph_args))
            return lambda state: jitted(state, *self.graph_args)

        if self.mesh is None:
            def core(state, *gargs):
                g = dict(zip(keys, gargs))
                return self._parts_step(state, state, g)
        else:
            P = PartitionSpec

            @functools.partial(jax.shard_map, mesh=self.mesh,
                               in_specs=(P(PARTS_AXIS),) * (1 + len(keys)),
                               out_specs=P(PARTS_AXIS))
            def core(state, *gargs):
                g = dict(zip(keys, gargs))
                # The per-iteration vertex-state exchange over ICI.
                with jax.named_scope("lux_exchange"):
                    full = jax.lax.all_gather(state, PARTS_AXIS,
                                              tiled=True)
                return self._parts_step(state, full, g)

        if self.program.name:
            core = jax.named_scope(f"lux_{self.program.name}")(core)
        self._step_core = core
        jitted = jax.jit(core, donate_argnums=0)
        self._register_variant(
            "step", jitted,
            lambda: (self._audit_state_sds, *self.graph_args))
        return lambda state: jitted(state, *self.graph_args)

    # -- static-audit surface (engine/auditable.py) --------------------

    # every lazily compiled loop variant, forced (built, not
    # compiled) so the registry is complete for a full audit
    _AUDIT_LAZY = ("_run_fused", "_run_stats_fused", "_run_until",
                   "_run_until_stats", "_run_health_fused",
                   "_run_until_health")

    # timed_phases phases whose measured seconds CONTAIN the step's
    # collectives — the comm observatory's attribution anchor
    # (lux_tpu/comms.py; observe._comm_attribution grades the wire
    # lower bound against exactly these phases)
    COMM_PHASES = ("exchange", "gen_exchange")

    @functools.cached_property
    def _audit_state_sds(self):
        """Abstract stand-in for the iterated state (shape/dtype from
        the program's init, no device placement).  The materialized
        init is STASHED for the next ``init_state`` call, so an
        audited-then-run engine (bench.py -audit) pays for exactly
        one host init, same as an unaudited one."""
        st = np.asarray(self.program.init(self.sg))
        self._pending_init = st
        return jax.ShapeDtypeStruct(st.shape, st.dtype)

    # -- public API ---------------------------------------------------

    def pure_step(self, state, *graph_args):
        """Un-jitted step taking the graph arrays as ARGUMENTS (pass
        ``*engine.graph_args``), so embedding jits don't bake hundreds
        of MB of edge indices in as constants (mesh=None engines)."""
        if self.mesh is not None:
            raise ValueError("pure_step is for single-device engines")
        return self._step_core(state, *graph_args)

    def step(self, state):
        """One iteration (compiled)."""
        return self._step_fn(state)

    @functools.cached_property
    def _run_fused(self):
        core = self._step_core

        @functools.partial(jax.jit, static_argnums=1, donate_argnums=0)
        def run(state, num_iters, *gargs):
            return jax.lax.fori_loop(
                0, num_iters, lambda _, s: core(s, *gargs), state)

        self._register_variant(
            "run", run,
            lambda: (self._audit_state_sds, 3, *self.graph_args))
        return lambda state, n: run(state, n, *self.graph_args)

    def run(self, state, num_iters: int, fused: bool = True,
            seg_budget: float | None = None):
        """num_iters iterations; fused=True compiles the whole loop into
        one XLA program (no host round-trips).  seg_budget (seconds)
        instead runs duration-budgeted fused segments
        (segmented.DurationBudget) so each XLA execution stays under
        the tunnel's ~55 s crash envelope (PERF_NOTES round 5) — the
        systematic form of the old hand-picked small-``ni`` routing."""
        if seg_budget is not None:
            from lux_tpu.segmented import DurationBudget, run_segments
            return run_segments(self, state, num_iters,
                                DurationBudget(seg_budget))
        if fused:
            if self.health:
                from lux_tpu import health as hw
                state, _it, _rb, _cb, _rbp, _cbp, h = \
                    self.run_health(state, num_iters)
                hw.ensure_ok(h, engine="pull", where="pull run")
                return state
            return self._run_fused(state, num_iters)
        for _ in range(num_iters):
            state = self.step(state)
        return state

    def _iter_counters(self, new, old):
        """Per-iteration device-side counters shared by the stats
        loops: (max-abs state change — the residual run_until
        converges on, count of vertices whose state changed), PLUS
        the round-13 per-part split (residual per part [P] float32,
        changed vertices per part [P] uint32).  The scalars are
        derived FROM the per-part rows (max of maxes / sum of sums),
        so max-over-parts and sum-over-parts are bitwise-exact by
        construction.  Computed on the sharded global arrays like
        _run_until's residual; O(state), tiny next to the O(edges)
        gather — and NO gathers at all (audit gather-budget holds)."""
        d = jnp.abs(new.astype(jnp.float32) - old.astype(jnp.float32))
        res_p = jnp.max(d.reshape(d.shape[0], -1), axis=1)     # [P]
        if d.ndim > 2:                        # K-vector payloads
            d = d.reshape(d.shape[0], d.shape[1], -1).max(axis=-1)
        chg_p = jnp.sum((d > 0).astype(jnp.uint32), axis=1)    # [P]
        return jnp.max(res_p), jnp.sum(chg_p), res_p, chg_p

    def _stats_bufs(self):
        cap, P = self.stats_cap, self.sg.num_parts
        return (jnp.zeros((cap,), jnp.float32),
                jnp.zeros((cap,), jnp.uint32),
                jnp.zeros((cap, P), jnp.float32),
                jnp.zeros((cap, P), jnp.uint32))

    @functools.cached_property
    def _run_stats_fused(self):
        core = self._step_core

        @functools.partial(jax.jit, static_argnums=1, donate_argnums=0)
        def run(state, num_iters, *gargs):
            def body(i, c):
                s, res, chg, resp, chgp = c
                new = core(s, *gargs)
                r, cnt, rp, cp = self._iter_counters(new, s)
                return (new, res.at[i].set(r, mode="drop"),
                        chg.at[i].set(cnt, mode="drop"),
                        resp.at[i].set(rp, mode="drop"),
                        chgp.at[i].set(cp, mode="drop"))

            return jax.lax.fori_loop(
                0, num_iters, body, (state, *self._stats_bufs()))

        self._register_variant(
            "run_stats", run,
            lambda: (self._audit_state_sds, 3, *self.graph_args))
        return lambda state, n: run(state, n, *self.graph_args)

    def run_stats(self, state, num_iters: int):
        """``run(fused=True)`` + device-side iteration counters
        accumulated inside the fori_loop: returns (state, residual
        float32 [stats_cap], changed uint32 [stats_cap], residual
        per part float32 [stats_cap, P], changed per part uint32
        [stats_cap, P]) where residual[i] is iteration i's max-abs
        state change and changed[i] its changed-vertex count (see
        lux_tpu/telemetry.py; writes past stats_cap drop).  The
        per-part counters are the imbalance-attribution signal:
        scalar = max/sum over the per-part row, bitwise
        (tests/test_telemetry.py holds the NumPy per-part oracle).
        Fetch the buffers once per run/segment — a few KB,
        independent of graph size."""
        return self._run_stats_fused(state, num_iters)

    @functools.cached_property
    def _run_until(self):
        core = self._step_core

        @functools.partial(jax.jit, donate_argnums=0)
        def run(state, tol, max_iters, *gargs):
            def cond(c):
                it, s, res = c
                # NOT (res <= tol), never (res > tol): a NaN residual
                # compares False BOTH ways, and the latter would exit
                # the loop reporting convergence on a garbage state
                # (round-9 tentpole).  Non-finite residuals keep
                # iterating until max_iters; run_until_health trips
                # the watchdog on them immediately.
                return jnp.logical_not(res <= tol) & (it < max_iters)

            def body(c):
                it, s, _ = c
                new = core(s, *gargs)
                res = jnp.max(jnp.abs(new.astype(jnp.float32) -
                                      s.astype(jnp.float32)))
                return it + 1, new, res

            it, s, res = jax.lax.while_loop(
                cond, body, (jnp.int32(0), state, jnp.float32(jnp.inf)))
            return s, it, res

        self._register_variant(
            "run_until", run,
            lambda: (self._audit_state_sds,
                     jax.ShapeDtypeStruct((), jnp.float32),
                     jax.ShapeDtypeStruct((), jnp.int32),
                     *self.graph_args))
        return run

    @functools.cached_property
    def _run_until_stats(self):
        core = self._step_core

        @functools.partial(jax.jit, donate_argnums=0)
        def run(state, tol, max_iters, *gargs):
            def cond(c):
                it, s, res = c[:3]
                # non-finite-safe, see _run_until's cond
                return jnp.logical_not(res <= tol) & (it < max_iters)

            def body(c):
                it, s, _res, rb, cb, rbp, cbp = c
                new = core(s, *gargs)
                r, cnt, rp, cp = self._iter_counters(new, s)
                return (it + 1, new, r,
                        rb.at[it].set(r, mode="drop"),
                        cb.at[it].set(cnt, mode="drop"),
                        rbp.at[it].set(rp, mode="drop"),
                        cbp.at[it].set(cp, mode="drop"))

            it, s, res, rb, cb, rbp, cbp = jax.lax.while_loop(
                cond, body,
                (jnp.int32(0), state, jnp.float32(jnp.inf),
                 *self._stats_bufs()))
            return s, it, res, rb, cb, rbp, cbp

        self._register_variant(
            "run_until_stats", run,
            lambda: (self._audit_state_sds,
                     jax.ShapeDtypeStruct((), jnp.float32),
                     jax.ShapeDtypeStruct((), jnp.int32),
                     *self.graph_args))
        return run

    def run_until_stats(self, state, tol: float,
                        max_iters: int = np.iinfo(np.int32).max):
        """``run_until`` + the per-iteration residual/changed counters
        of ``run_stats`` (per-part counters included, same oracle
        contract) — closing the 'pull residuals are invisible inside
        run_until' observability hole.  Returns (state, it, residual,
        residual_buf, changed_buf, residual_parts, changed_parts)."""
        return self._run_until_stats(state, jnp.float32(tol),
                                     jnp.int32(max_iters),
                                     *self.graph_args)

    def run_until(self, state, tol: float,
                  max_iters: int = np.iinfo(np.int32).max):
        """Iterate until the max-abs change of the STATE (whatever
        the program iterates — e.g. pagerank's degree-scaled ranks)
        falls to ``tol``, or max_iters, entirely inside one XLA
        program — convergence-driven runs the reference lacks (fixed
        -ni only, reference pagerank.cc:109-114).  Returns
        (state, iterations, final_residual) as device scalars."""
        return self._run_until(state, jnp.float32(tol),
                               jnp.int32(max_iters), *self.graph_args)

    # -- health-watchdog loop variants (lux_tpu/health.py) -------------

    @functools.cached_property
    def _run_health_fused(self):
        """run_stats + the in-loop health word: a while_loop (num_iters
        is a traced argument — one compiled program for every segment
        size) whose condition ALSO exits the iteration after a check
        trips, so a diverging run stops burning device time the moment
        the watchdog sees it."""
        from lux_tpu import health as hw
        core = self._step_core

        @functools.partial(jax.jit, donate_argnums=0)
        def run(state, num_iters, h0, win0, *gargs):
            def cond(c):
                it, h = c[0], c[6]
                return (it < num_iters) & (h[0] == 0)

            def body(c):
                it, s, rb, cb, rbp, cbp, h, win = c
                new = core(s, *gargs)
                r, cnt, rp, cp = self._iter_counters(new, s)
                h, win = hw.pull_update(h, win, new, r)
                return (it + 1, new, rb.at[it].set(r, mode="drop"),
                        cb.at[it].set(cnt, mode="drop"),
                        rbp.at[it].set(rp, mode="drop"),
                        cbp.at[it].set(cp, mode="drop"), h, win)

            it, s, rb, cb, rbp, cbp, h, win = jax.lax.while_loop(
                cond, body,
                (jnp.int32(0), state, *self._stats_bufs(), h0, win0))
            return s, it, rb, cb, rbp, cbp, h, win

        def call(state, n, watch=None):
            if watch is None:
                watch = (hw.init_word(), hw.init_window())
            s, it, rb, cb, rbp, cbp, h, win = run(
                state, jnp.int32(n), *watch, *self.graph_args)
            return s, it, rb, cb, rbp, cbp, (h, win)

        self._register_variant(
            "run_health", run,
            lambda: (self._audit_state_sds,
                     jax.ShapeDtypeStruct((), jnp.int32),
                     hw.init_word(), hw.init_window(),
                     *self.graph_args))
        return call

    def run_health(self, state, num_iters: int, watch=None):
        """``run_stats`` under the device-side health watchdog
        (per-part counters included, same oracle contract): returns
        (state, iters_executed, residual_buf, changed_buf,
        residual_parts, changed_parts, watch) where watch = (health
        int32[6], residual window).  The
        loop EXITS the iteration a check trips (iters_executed <
        num_iters then); fetch + decode the word once per run/segment
        with ``health.ensure_ok(watch)`` — 24 bytes, no in-loop host
        syncs.  Pass the previous segment's ``watch`` back in so the
        trailing-window checks keep their history across segment
        boundaries.  Compiled lazily; the watchdog-free programs are
        untouched."""
        return self._run_health_fused(state, num_iters, watch)

    @functools.cached_property
    def _run_until_health(self):
        from lux_tpu import health as hw
        core = self._step_core

        @functools.partial(jax.jit, donate_argnums=0)
        def run(state, tol, max_iters, *gargs):
            def cond(c):
                it, res, h = c[0], c[2], c[7]
                return (jnp.logical_not(res <= tol)
                        & (it < max_iters) & (h[0] == 0))

            def body(c):
                it, s, _res, rb, cb, rbp, cbp, h, win = c
                new = core(s, *gargs)
                r, cnt, rp, cp = self._iter_counters(new, s)
                h, win = hw.pull_update(h, win, new, r)
                return (it + 1, new, r,
                        rb.at[it].set(r, mode="drop"),
                        cb.at[it].set(cnt, mode="drop"),
                        rbp.at[it].set(rp, mode="drop"),
                        cbp.at[it].set(cp, mode="drop"), h, win)

            it, s, res, rb, cb, rbp, cbp, h, win = jax.lax.while_loop(
                cond, body,
                (jnp.int32(0), state, jnp.float32(jnp.inf),
                 *self._stats_bufs(), hw.init_word(),
                 hw.init_window()))
            return s, it, res, rb, cb, rbp, cbp, h, win

        self._register_variant(
            "run_until_health", run,
            lambda: (self._audit_state_sds,
                     jax.ShapeDtypeStruct((), jnp.float32),
                     jax.ShapeDtypeStruct((), jnp.int32),
                     *self.graph_args))
        return run

    def run_until_health(self, state, tol: float,
                         max_iters: int = np.iinfo(np.int32).max):
        """``run_until_stats`` under the health watchdog (per-part
        counters included, same oracle contract): returns (state, it,
        residual, residual_buf, changed_buf, residual_parts,
        changed_parts, watch) with watch = (health int32[6], residual
        window).  The non-finite-safe predicate means a NaN residual
        can never report convergence; the watchdog additionally stops
        the loop at the tripping iteration instead of spinning to
        max_iters."""
        s, it, res, rb, cb, rbp, cbp, h, win = self._run_until_health(
            state, jnp.float32(tol), jnp.int32(max_iters),
            *self.graph_args)
        return s, it, res, rb, cb, rbp, cbp, (h, win)

    def unpad(self, state) -> np.ndarray:
        """Padded device state -> [nv, ...] user order (host).
        Multi-host runs gather remote shards over the process group."""
        from lux_tpu.parallel.multihost import fetch_global
        return self.sg.from_padded(fetch_global(state))

    # -- per-iteration phase observability ----------------------------

    @functools.cached_property
    def _phase_jits(self):
        """One compiled program per phase (exchange / gather / reduce /
        apply), each returning (output, scalar checksum) — the scalar
        fetch is the tunnel-safe completion fence.  Separate
        executables deliberately prevent cross-phase fusion, so the
        split is honest at the cost of materializing phase outputs."""
        from lux_tpu.engine.phased import cksum, mesh_wrap

        keys = self._graph_keys
        sg = self.sg

        if (self.program.edge_value_from_dot is not None
                and (self.tiles is not None
                     or self.page_plan is not None)):
            # dot-path programs (colfilter): the src gather, MXU tile
            # dots and one-hot reduction are one lax.map pipeline by
            # design, so they time as ONE 'dot_reduce' phase — closing
            # the round-2 hole where this raised NotImplementedError
            # (paged engines time their page-fetch + shuffle + SDDMM
            # pipeline under the same phase name)
            def dot_exchange(state, *gargs):
                full = state
                if self.mesh is not None:
                    full = jax.lax.all_gather(state, PARTS_AXIS,
                                              tiled=True)
                flat = full.reshape((sg.num_parts * sg.vpad,) +
                                    full.shape[2:])
                return flat, cksum(flat)

            def dot_reduce(flat, state, *gargs):
                g = dict(zip(keys, gargs))
                if self.page_plan is not None:
                    red = jax.vmap(
                        lambda old, gp: self._paged_dot_red(flat, gp))(
                        state, g)
                else:
                    red = jax.vmap(
                        lambda old, gp: self._part_dot_red(
                            flat, old, gp))(state, g)
                return red, cksum(red)

            def dot_apply(state, red, *gargs):
                g = dict(zip(keys, gargs))
                new = jax.vmap(self._apply_epilogue)(state, red, g)
                return new, cksum(new)

            fns = dict(exchange=dot_exchange, dot_reduce=dot_reduce,
                       apply=dot_apply)
            if self.mesh is not None:
                P = PartitionSpec
                S, R = P(PARTS_AXIS), P()
                wrap = mesh_wrap(self.mesh, len(keys), S, R)
                fns = dict(exchange=wrap(dot_exchange, (S,), R),
                           dot_reduce=wrap(dot_reduce, (R, S), S),
                           apply=wrap(dot_apply, (S, S), S))
            return {k: jax.jit(f) for k, f in fns.items()}
        # dot-path programs on the FLAT layout never take the dot
        # shortcut (it requires tiles, see use_dot in _parts_step), so
        # their compiled step IS the generic gather/reduce pipeline
        # below — time it with the generic phases (closes the last
        # round-4 stub, VERDICT weak #6)

        if self.exchange == "owner":
            # owner mode has no separable gather: generation (scan
            # over source parts, small-shard gathers) and the
            # reduce_scatter exchange are one fused phase by design
            def gen_exchange(state, *gargs):
                g = dict(zip(keys, gargs))
                acc = self._owner_contribs(state, g)
                red = self._owner_exchange(acc)[:, :sg.vpad]
                return red, cksum(red)

            def owner_apply(state, red, *gargs):
                g = dict(zip(keys, gargs))
                flat = None
                if self.pairs is not None:
                    full = (state if self.mesh is None else
                            jax.lax.all_gather(state, PARTS_AXIS,
                                               tiled=True))
                    flat = full.reshape((sg.num_parts * sg.vpad,) +
                                        full.shape[2:])
                new = self._owner_apply(state, red, flat, g)
                return new, cksum(new)

            fns = dict(gen_exchange=gen_exchange, apply=owner_apply)
            if self.mesh is not None:
                P = PartitionSpec
                S, R = P(PARTS_AXIS), P()
                wrap = mesh_wrap(self.mesh, len(keys), S, R)
                fns = dict(gen_exchange=wrap(gen_exchange, (S,), S),
                           apply=wrap(owner_apply, (S, S), S))
            return {k: jax.jit(f) for k, f in fns.items()}

        def exchange(state, *gargs):
            full = state
            if self.mesh is not None:
                full = jax.lax.all_gather(state, PARTS_AXIS, tiled=True)
            flat = full.reshape((sg.num_parts * sg.vpad,) +
                                full.shape[2:])
            return flat, cksum(flat)

        def gather(flat, state, *gargs):
            g = dict(zip(keys, gargs))
            msgs = jax.vmap(
                lambda old, gp: self._part_msgs(flat, old, gp))(state, g)
            return msgs, cksum(msgs)

        def reduce(flat, msgs, *gargs):
            g = dict(zip(keys, gargs))
            red = jax.vmap(
                lambda m, gp: self._part_reduce(flat, m, gp))(msgs, g)
            return red, cksum(red)

        def gather_reduce(flat, state, *gargs):
            # the streamed step fuses gather+message+reduce per chunk
            # block — instrument it as ONE phase so the report reflects
            # what the compiled step actually runs (and stays within
            # the memory bound streaming exists for).  Paged engines
            # fuse page-fetch + lane shuffle + reduce the same way.
            g = dict(zip(keys, gargs))
            if self.page_plan is not None:
                red = jax.vmap(lambda gp: self._paged_red(flat, gp))(g)
            else:
                red = jax.vmap(
                    lambda gp: self._part_red_streamed(flat, gp))(g)
            return red, cksum(red)

        def apply(state, red, *gargs):
            g = dict(zip(keys, gargs))
            new = jax.vmap(self._apply_epilogue)(state, red, g)
            return new, cksum(new)

        if self._streams or self.page_plan is not None:
            fns = dict(exchange=exchange, gather_reduce=gather_reduce,
                       apply=apply)
            specs = dict(exchange=((0,), 1), gather_reduce=((1, 0), 0),
                         apply=((0, 0), 0))
        else:
            fns = dict(exchange=exchange, gather=gather, reduce=reduce,
                       apply=apply)
            specs = dict(exchange=((0,), 1), gather=((1, 0), 0),
                         reduce=((1, 0), 0), apply=((0, 0), 0))
        if self.mesh is not None:
            P = PartitionSpec
            S, R = P(PARTS_AXIS), P()
            wrap = mesh_wrap(self.mesh, len(keys), S, R)
            fns = {name: wrap(fn,
                              tuple(R if r else S
                                    for r in specs[name][0]),
                              R if specs[name][1] else S)
                   for name, fn in fns.items()}
        return {k: jax.jit(f) for k, f in fns.items()}

    def timed_phases(self, state, iters: int = 1):
        """Instrumented stepwise iterations -> (state, [{phase: s}]).

        The analogue of the reference's per-iteration per-part
        loadTime/compTime/updateTime -verbose prints (reference
        sssp_gpu.cu:513-518).  Phases run as SEPARATE fenced programs
        (engine/phased.py), so absolute times carry dispatch overhead
        the fused run does not; read them for relative weight, not for
        GTEPS."""
        from lux_tpu.engine.phased import PhaseTimer
        from lux_tpu.timing import fetch
        jits = self._phase_jits
        gargs = self.graph_args
        report = []
        for _ in range(iters):
            pt = PhaseTimer(fetch)
            if "gen_exchange" in jits:    # owner exchange: two phases
                red = pt("gen_exchange", jits["gen_exchange"], state,
                         *gargs)
                state = pt("apply", jits["apply"], state, red, *gargs)
                report.append(pt.t)
                continue
            flat = pt("exchange", jits["exchange"], state, *gargs)
            if "dot_reduce" in jits:      # dot path: one reduce phase
                red = pt("dot_reduce", jits["dot_reduce"], flat,
                         state, *gargs)
            elif "gather_reduce" in jits:  # streamed step: one phase
                red = pt("gather_reduce", jits["gather_reduce"], flat,
                         state, *gargs)
            else:
                msgs = pt("gather", jits["gather"], flat, state, *gargs)
                red = pt("reduce", jits["reduce"], flat, msgs, *gargs)
            state = pt("apply", jits["apply"], state, red, *gargs)
            report.append(pt.t)
        return state, report


def _check_local_parts(sg, mesh, pair_threshold):
    """Validate a local-parts (multi-host) ShardedGraph against the
    mesh: the materialized rows must be exactly the rows this process's
    devices hold under the parts sharding."""
    if sg.local_parts is None:
        return
    if mesh is None:
        raise ValueError(
            "a ShardedGraph built with parts= (multi-host local rows) "
            "requires a mesh")
    # pair_threshold IS supported with local-parts builds: the pair
    # planner lays each process's rows out against a process-group-
    # allreduced common depth profile (plan_sharded_pairs)
    del pair_threshold
    from lux_tpu.parallel.mesh import local_part_rows
    expect = local_part_rows(mesh, sg.num_parts)
    got = list(np.asarray(sg.local_parts))
    if got != expect:
        raise ValueError(
            f"local_parts {got} != this process's sharding rows "
            f"{expect}; build with parts=multihost.process_parts(P)")
