"""The pull engine: dense gather-apply iterations.

One iteration (the analogue of one PullAppTask index launch,
reference pull_model.inl:423-470 + pagerank_gpu.cu:104-151):

1. make the full vertex state visible to every part — single device:
   a reshape; mesh: ``lax.all_gather`` over the ``parts`` axis (the
   reference's whole-region READ_ONLY requirement that Legion/GASNet
   materialize remotely, pull_model.inl:454-461);
2. gather each edge's source state by precomputed padded slot;
3. per-edge message (program.edge_value);
4. sorted segmented reduction to each part's local destinations
   (replacing the CUB BlockScan + atomicAdd CTA pattern, SURVEY.md §3.3);
5. per-vertex apply epilogue.

Fixed-iteration runs are fused into a single XLA program with
``lax.fori_loop`` — the TPU-native version of the reference's
fire-and-forget launch pipeline (pagerank.cc:109-114), with zero host
round-trips instead of deferred-execution tricks.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from lux_tpu.engine.program import PartCtx, PullProgram
from lux_tpu.graph import ShardedGraph
from lux_tpu.ops.segment import segment_reduce
from lux_tpu.parallel.mesh import PARTS_AXIS, parts_spec, shard_over_parts

_GRAPH_KEYS = ("src_slot", "dst_local", "weight", "deg", "vmask")


class PullEngine:
    """Compiled pull-model iterations for one ShardedGraph + program.

    With ``mesh=None`` everything runs on one device (parts stacked on
    the leading axis, vmapped).  With a mesh, all part-major arrays are
    sharded over the ``parts`` axis and the same per-part computation
    runs under shard_map with an all-gather for remote state.
    """

    def __init__(self, sg: ShardedGraph, program: PullProgram, mesh=None):
        if mesh is not None and sg.num_parts % mesh.devices.size != 0:
            raise ValueError(
                f"num_parts={sg.num_parts} not divisible by mesh size "
                f"{mesh.devices.size}")
        self.sg = sg
        self.program = program
        self.mesh = mesh

        arrays = dict(
            src_slot=jnp.asarray(sg.src_slot),
            dst_local=jnp.asarray(sg.dst_local),
            weight=(jnp.asarray(sg.edge_weight) if sg.weighted else None),
            deg=jnp.asarray(sg.deg_padded),
            vmask=jnp.asarray(sg.vmask),
        )
        if mesh is not None:
            arrays = shard_over_parts(mesh, arrays)
        self.arrays = arrays
        self._step_fn = self._build_step()

    # -- state placement ----------------------------------------------

    def init_state(self):
        state = jnp.asarray(self.program.init(self.sg))
        if self.mesh is not None:
            state = jax.device_put(state, parts_spec(self.mesh))
        return state

    # -- one part's work ----------------------------------------------

    def _part_step(self, flat_state, old_p, g):
        """g: dict of this part's graph arrays."""
        prog, sg = self.program, self.sg
        src_vals = jnp.take(flat_state, g["src_slot"], axis=0)
        dst_vals = (jnp.take(old_p, jnp.minimum(g["dst_local"],
                                                sg.vpad - 1), axis=0)
                    if prog.needs_dst else None)
        msgs = prog.edge_value(src_vals, dst_vals, g["weight"])
        red = segment_reduce(msgs, g["dst_local"], sg.vpad + 1,
                             prog.reduce)[:sg.vpad]
        ctx = PartCtx(deg=g["deg"], vmask=g["vmask"], nv=sg.nv, ne=sg.ne)
        new = prog.apply(old_p, red, ctx)
        keep = g["vmask"].reshape(g["vmask"].shape +
                                  (1,) * (new.ndim - 1))
        return jnp.where(keep, new, old_p)

    def _parts_step(self, local_state, full_state, g_local):
        """vmap _part_step over this device's parts."""
        sg = self.sg
        flat = full_state.reshape((sg.num_parts * sg.vpad,) +
                                  full_state.shape[2:])
        has_w = g_local["weight"] is not None

        def one(src_slot, dst_local, weight, old, deg, vmask):
            g = dict(src_slot=src_slot, dst_local=dst_local,
                     weight=weight, deg=deg, vmask=vmask)
            return self._part_step(flat, old, g)

        if has_w:
            return jax.vmap(one)(
                g_local["src_slot"], g_local["dst_local"],
                g_local["weight"], local_state, g_local["deg"],
                g_local["vmask"])
        return jax.vmap(lambda s, d, o, dg, vm: one(s, d, None, o, dg, vm))(
            g_local["src_slot"], g_local["dst_local"], local_state,
            g_local["deg"], g_local["vmask"])

    # -- full step over all parts -------------------------------------

    def _build_step(self):
        """Builds self._graph_args and the un-jitted core
        step(state, *graph_args); returns a jitted single-step wrapper.

        Graph arrays are always passed as ARGUMENTS, never closed over:
        closing over them would bake hundreds of MB of edge indices
        into the XLA program as constants.
        """
        a = self.arrays
        has_w = a["weight"] is not None
        keys = [k for k in _GRAPH_KEYS if not (k == "weight" and not has_w)]
        self._graph_keys = keys
        self.graph_args = tuple(a[k] for k in keys)

        if self.mesh is None:
            def core(state, *gargs):
                g = dict(zip(keys, gargs), **({} if has_w
                                              else {"weight": None}))
                return self._parts_step(state, state, g)
        else:
            P = PartitionSpec
            in_specs = (P(PARTS_AXIS),) * (1 + len(keys))

            @functools.partial(jax.shard_map, mesh=self.mesh,
                               in_specs=in_specs,
                               out_specs=P(PARTS_AXIS))
            def core(state, *gargs):
                g = dict(zip(keys, gargs), **({} if has_w
                                              else {"weight": None}))
                # The per-iteration vertex-state exchange over ICI.
                full = jax.lax.all_gather(state, PARTS_AXIS, tiled=True)
                return self._parts_step(state, full, g)

        self._step_core = core
        jitted = jax.jit(core, donate_argnums=0)
        return lambda state: jitted(state, *self.graph_args)

    # -- public API ---------------------------------------------------

    def pure_step(self, state, *graph_args):
        """Un-jitted step taking the graph arrays as ARGUMENTS (pass
        ``*engine.graph_args``), so embedding jits don't bake hundreds
        of MB of edge indices in as constants (mesh=None engines)."""
        if self.mesh is not None:
            raise ValueError("pure_step is for single-device engines")
        return self._step_core(state, *graph_args)

    def step(self, state):
        """One iteration (compiled)."""
        return self._step_fn(state)

    @functools.cached_property
    def _run_fused(self):
        core = self._step_core

        @functools.partial(jax.jit, static_argnums=1, donate_argnums=0)
        def run(state, num_iters, *gargs):
            return jax.lax.fori_loop(
                0, num_iters, lambda _, s: core(s, *gargs), state)

        return lambda state, n: run(state, n, *self.graph_args)

    def run(self, state, num_iters: int, fused: bool = True):
        """num_iters iterations; fused=True compiles the whole loop into
        one XLA program (no host round-trips)."""
        if fused:
            return self._run_fused(state, num_iters)
        for _ in range(num_iters):
            state = self.step(state)
        return state

    def unpad(self, state) -> np.ndarray:
        """Padded device state -> [nv, ...] user order (host)."""
        return self.sg.from_padded(np.asarray(jax.device_get(state)))
