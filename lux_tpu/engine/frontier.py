"""Sparse-frontier machinery for the push engine.

The reference keeps per-partition frontier queues with a
dense-bitmap / sparse-queue state machine and switches representation
on occupancy (reference graph.h:100-106, sssp_gpu.cu:408-491,
SURVEY.md §3.4).  On TPU, variable-size queues fight XLA's static
shapes, so the design is:

- The CANONICAL frontier is always the dense bool mask (shape-stable,
  trivially all-gatherable).  The sparse path is an *execution
  strategy*, not a distinct representation: when the active count is
  small, the step compacts the mask into a capacity-bounded padded
  queue of (vertex slot, label) pairs and relaxes ONLY the frontier's
  out-edges — a fixed edge budget ``EB`` of work instead of a full
  pass over every edge.
- Queue capacity mirrors the reference's sizing rule
  (``part_nv/SPARSE_THRESHOLD + 100``, push_model.inl:393-397); the
  caller falls back to the dense step (lax.cond) when the frontier
  overflows either the queue or the edge budget, which is exactly the
  reference's sparse->dense overflow transition (sssp_gpu.cu:485-490).
- Labels ride along with vertex ids in the queue (the reference
  gathers them from the all-parts dist region instead), so multi-chip
  sparse iterations exchange O(queue) bytes over ICI, not O(nv).

Everything here is per-part, static-shape, and built from sorted
cumsum/gather primitives — no data-dependent shapes anywhere.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Block length for the MXU cumsum-as-matmul in expand_frontier: one
# int8 lower-triangular [B, B] matrix (64 KB) contracted per block,
# same sizing rationale as ops/tiled.MXU_SCAN_BLOCK.
FRONTIER_MXU_BLOCK = 256


def _cumsum_matmul(x, block: int = FRONTIER_MXU_BLOCK):
    """Inclusive cumsum of an int32 [N] vector as blocked lower-
    triangular matmuls (the tiled scan-as-matmul recurrence with one
    global segment): per block ``T @ x_b + carry`` where T[i, j] =
    (i >= j) is built on device from iota.  Bitwise-equal to
    jnp.cumsum for int32 (integer matmul is exact)."""
    N = x.shape[0]
    nB = -(-N // block)
    Np = nB * block
    if Np != N:
        x = jnp.concatenate(
            [x, jnp.zeros((Np - N,), x.dtype)], axis=0)
    ii = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    jj = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
    T = (ii >= jj).astype(jnp.int8)

    def step(carry, x_b):
        inner = jnp.einsum("ij,j->i", T, x_b,
                           preferred_element_type=x.dtype)
        out = inner + carry
        return out[-1], out

    _, blocks = jax.lax.scan(step, jnp.zeros((), x.dtype),
                             x.reshape(nB, block))
    return blocks.reshape(Np)[:N]


def compact_mask(mask, labels, capacity: int):
    """Dense bool mask [vpad] -> padded queue.

    Returns (ids int32 [capacity], vals [capacity], count int32).
    ids[i] for i >= count is vpad (an invalid slot); callers mask on
    position < count.  If count > capacity the queue is truncated —
    callers must branch to the dense path in that case.
    """
    with jax.named_scope("lux_sparse_compact"):
        vpad = mask.shape[0]
        ranks = jnp.cumsum(mask.astype(jnp.int32))      # 1-based
        count = ranks[-1]
        # i-th set bit = first position whose running count reaches
        # i+1; vectorized binary search over the monotone ranks array.
        want = jnp.arange(capacity, dtype=jnp.int32) + 1
        ids = jnp.searchsorted(ranks, want, side="left",
                               method="scan_unrolled").astype(jnp.int32)
        valid = want <= count
        ids = jnp.where(valid, ids, vpad)
        vals = jnp.take(labels, jnp.minimum(ids, vpad - 1), axis=0)
        return ids, vals, count


def expand_frontier(ids, vals, src_ids, src_off, nv: int,
                    edge_budget: int, use_mxu: bool = False):
    """Map a gathered queue to its out-edge slots in this part.

    ids     int32 [Q]   vertex GLOBAL ids (graph numbering), nv=invalid
    vals    [Q]         the queue vertices' labels
    src_ids int32 [S]   this part's present-source ids, sorted, pad=nv
    src_off int32 [S+1] END offsets into the part's src-sorted edge
                        arrays (ShardedGraph.src_sorted — the
                        compressed replacement for the reference's
                        nv-wide row pointers, push_model.inl:321-324)
    Returns (edge_idx int32 [EB], src_val [EB], in_range bool [EB],
             total int32, off int32 [Q]) where edge_idx indexes the
    part's src-sorted edge arrays, src_val is the owning queue item's
    label, off is the running END offset of each queue item's out-edge
    extent (off[-1] == total), and total is the real number of
    frontier out-edges here (may exceed EB — callers must then use the
    dense path; entries past ``total`` are masked by in_range).
    """
    with jax.named_scope("lux_sparse_expand"):
        Q = ids.shape[0]
        S = src_ids.shape[0]
        # binary-search each queue id in the compressed source index
        pos = jnp.searchsorted(src_ids, ids, side="left",
                               method="scan_unrolled")
        posc = jnp.minimum(pos, S - 1).astype(jnp.int32)
        present = (jnp.take(src_ids, posc, axis=0) == ids) & (ids < nv)
        begin = jnp.where(present, jnp.take(src_off, posc, axis=0), 0)
        end = jnp.where(present, jnp.take(src_off, posc + 1, axis=0), 0)
        deg = (end - begin).astype(jnp.int32)
        off = jnp.cumsum(deg)                   # END offsets per item
        total = off[-1]
        start = off - deg                       # begin offset per item
        # Owner of each edge slot via the CSR-expand trick: drop each
        # item's 1-based queue index at its first slot, then a running
        # max spreads it across the item's extent.  (Items with
        # deg > 0 have distinct starts, so the scatter-max never
        # collides.)
        marks = jnp.zeros((edge_budget + 1,), jnp.int32)
        qidx = jnp.arange(Q, dtype=jnp.int32) + 1
        if use_mxu:
            # MXU form: because deg > 0 items have strictly increasing
            # starts AND increasing qidx, the running max of scattered
            # qidx equals the running SUM of scattered qidx-DELTAS
            # (delta = qidx - previous deg>0 item's qidx telescopes,
            # so every prefix sum lands exactly on the most recent
            # item's qidx — including the clamped edge_budget slot,
            # where colliding overflow deltas telescope to the last
            # overflow qidx).  Scatter-ADD into a zero-filled buffer
            # IS the identity init (0 = sum identity), so the
            # identity-init audit passes this path without a pragma;
            # the cumsum then runs as blocked triangular matmuls.
            qm = jnp.where(deg > 0, qidx, 0)
            run = jax.lax.cummax(qm)                 # cheap [Q] op
            prev = jnp.concatenate(
                [jnp.zeros((1,), jnp.int32), run[:-1]], axis=0)
            delta = jnp.where(deg > 0, qidx - prev, 0)
            marks = marks.at[jnp.minimum(start, edge_budget)].add(delta)
            owner = _cumsum_matmul(marks[:edge_budget]) - 1  # [EB]
        else:
            # audit: allow(identity-init) — 0 deliberately marks "no
            # item starts here": values are 1-based queue indices
            # >= 1, and the cummax - 1 below maps an untouched 0 back
            # to no-owner (an int32-min init would overflow that - 1).
            marks = marks.at[jnp.minimum(start, edge_budget)].max(
                jnp.where(deg > 0, qidx, 0))
            owner = jax.lax.cummax(marks[:edge_budget]) - 1  # [EB]
        owner = jnp.maximum(owner, 0)
        slot = jnp.arange(edge_budget, dtype=off.dtype)
        in_range = slot < jnp.minimum(total, edge_budget)
        within = slot - jnp.take(start, owner, axis=0)
        edge_idx = (jnp.take(begin, owner, axis=0)
                    + within).astype(jnp.int32)
        edge_idx = jnp.where(in_range, edge_idx, 0)
        src_val = jnp.take(vals, owner, axis=0)
        return edge_idx, src_val, in_range, total, off


def scatter_reduce(labels, dst_local, cand, kind: str):
    """Scatter-combine candidates into per-part labels.

    dst_local indexes [0, vpad); out-of-frontier lanes should carry the
    reduction identity so they are no-ops.  Unsorted scatter — only used
    on the bounded sparse edge budget, never on full edge arrays.
    """
    with jax.named_scope("lux_sparse_scatter"):
        vpad = labels.shape[0]
        safe = jnp.minimum(dst_local, vpad - 1)
        if kind == "min":
            return labels.at[safe].min(cand, mode="drop")
        if kind == "max":
            return labels.at[safe].max(cand, mode="drop")
    raise ValueError(f"unsupported sparse reduce {kind!r}")
