"""Shared static-audit surface for the engines (lux_tpu/audit.py).

Both engines register every compiled loop variant as
``(jitted fn, example-args thunk)`` so the auditor can trace the
EXACT programs the engine runs (reference analogue: the compile-time
template contract of core/graph.h:146-225, here checked post-trace
instead of pre-compile).  The thunks build abstract
``ShapeDtypeStruct`` stand-ins where possible; the one materialized
host init they require is stashed in ``_pending_init`` for the next
``init_state`` call, so an audited-then-run engine pays for exactly
one init.
"""

from __future__ import annotations


class AuditableEngine:
    """Mixin: compiled-variant registry + lazy-variant forcing, plus
    the shared PLACEMENT surface (round 11): both engines place state
    with the same (sg, mesh, exchange) triple, and the elastic
    recovery path (lux_tpu/resilience.py, checkpoint.py) reasons
    about placement through ``ndev`` / ``placement_meta`` instead of
    poking at engine internals.

    Subclasses set ``_AUDIT_LAZY`` (attribute names whose
    cached_property builders register variants) and populate
    ``self._audit_variants = {}`` before building programs.
    """

    _AUDIT_LAZY: tuple = ()

    @property
    def ndev(self) -> int:
        """Devices this engine's state is placed over (1 = no mesh)."""
        mesh = getattr(self, "mesh", None)
        return 1 if mesh is None else int(mesh.devices.size)

    def placement_meta(self) -> dict:
        """The placement/config fingerprint checkpoints record
        (checkpoint.py): a resume validates num_parts/vpad/exchange
        (P and the padded layout are FIXED across a recovery; a
        different exchange mode is a different float-reduction order,
        so silently resuming across one would break bitwise
        reproducibility), while an ``ndev`` difference is the
        RE-PLACEMENT contract — the global host view re-shards onto
        any mesh whose size divides num_parts."""
        sg = self.sg
        return {"ndev": self.ndev,
                "num_parts": int(sg.num_parts),
                "vpad": int(sg.vpad),
                "exchange": getattr(self, "exchange", None)}

    def _register_variant(self, name, jitted, args_thunk):
        """Expose one compiled loop variant to the static program
        auditor: the jitted callable plus a thunk building example
        (abstract where possible) arguments for ``jitted.trace`` —
        the auditor only traces, it never executes or compiles."""
        self._audit_variants[name] = (jitted, args_thunk)

    def audit_programs(self):
        """name -> (jitted, example-args thunk) for every program
        variant this engine can run, the lazily compiled ones forced
        (built, not compiled)."""
        for attr in self._AUDIT_LAZY:
            getattr(self, attr)
        return dict(self._audit_variants)

    def audit_variant(self, name: str):
        """One registered variant WITHOUT forcing the lazy builds —
        the comm observatory's entry (lux_tpu/comms.py traces only
        the per-iteration "step" program, which both engines register
        eagerly at build time)."""
        try:
            return self._audit_variants[name]
        except KeyError:
            raise KeyError(
                f"no registered program variant {name!r} "
                f"(have {sorted(self._audit_variants)}; lazy "
                f"variants appear after audit_programs())") from None

    def comm_ledger(self, check: bool = True):
        """This engine's per-iteration communication ledger
        (lux_tpu/comms.ledger_for): every collective of the "step"
        program priced in wire bytes and cross-checked against the
        NumPy message-count oracle.  Tracing only — no compile, no
        execution."""
        from lux_tpu import comms
        return comms.ledger_for(self, check=check)

    def _consume_pending_init(self):
        """The audit's init probe, if one is stashed (see
        ``_audit_state_sds`` in each engine) — consumed at most once.
        Program inits in this repo are pure functions of sg, so the
        stashed first init IS the init."""
        pending = getattr(self, "_pending_init", None)
        self._pending_init = None
        return pending

    def _drop_pending_init(self):
        """Release the stash without consuming it — called by
        ``place()`` (the checkpoint-resume path): a caller placing
        external state will never need the probe, and holding a full
        padded host init for the engine's lifetime is GBs at scale."""
        self._pending_init = None
