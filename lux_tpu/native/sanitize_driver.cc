// Sanitizer exerciser for the native tools (make -C lux_tpu/native
// sanitize): runs the 3-edge smoke graph through the loader, a tiny
// R-MAT generation, and the threaded radix sort — compiled with
// -fsanitize=address,undefined -Wall -Werror so memory errors and UB
// in loader.cc/rmat.cc/sort.cc fail the (slow-marked) tier test
// instead of corrupting a multi-GB benchmark load.  Mirrors the
// checks tests/test_native_smoke.py does from Python, where the
// ctypes .so cannot practically run under ASan.
#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

extern "C" {
int lux_read_header(const char* path, uint32_t* nv, uint64_t* ne);
int lux_load_partition(const char* path, uint32_t nv, uint64_t ne,
                       uint32_t v0, uint32_t v1, int weighted,
                       uint32_t weight_size, uint64_t* e_lo,
                       uint64_t* e_hi, uint64_t* row_out,
                       uint32_t* col_out, void* weight_out,
                       int threads);
int lux_count_degrees(const char* path, uint32_t nv, uint64_t ne,
                      uint32_t* deg_out, int threads);
int lux_rmat_csc(int scale, int edge_factor, uint64_t seed, double pa,
                 double pb, double pc, uint64_t* row_ptrs,
                 uint32_t* col_idx, uint32_t* degrees);
int lux_sort_kv_u64(uint64_t* keys, uint64_t* key_tmp, int64_t n,
                    int threads, int n_pay, void** pay,
                    void** pay_tmp, const int32_t* pay_size);
int lux_argsort_u64(const uint64_t* keys, int64_t n, int threads,
                    int64_t* perm_out);
int lux_reorder_cluster(uint32_t nv, uint64_t ne, const uint32_t* src,
                        const uint32_t* dst, int hubs_first,
                        uint32_t* perm_out);
}

#define CHECK(cond)                                                \
  do {                                                             \
    if (!(cond)) {                                                 \
      std::fprintf(stderr, "sanitize_driver: FAILED %s (%s:%d)\n", \
                   #cond, __FILE__, __LINE__);                     \
      return 1;                                                    \
    }                                                              \
  } while (0)

static int smoke_loader(const char* path) {
  // the converter's 3-edge weighted smoke graph: dst-sorted edges
  // 2->0 (w=1), 0->1 (w=5), 1->2 (w=3)
  uint32_t nv = 0;
  uint64_t ne = 0;
  CHECK(lux_read_header(path, &nv, &ne) == 0);
  CHECK(nv == 3 && ne == 3);

  std::vector<uint32_t> deg(nv);
  CHECK(lux_count_degrees(path, nv, ne, deg.data(), 2) == 0);
  CHECK(deg[0] == 1 && deg[1] == 1 && deg[2] == 1);

  uint64_t e_lo = 0, e_hi = 0;
  CHECK(lux_load_partition(path, nv, ne, 0, nv, 1, 4, &e_lo, &e_hi,
                           nullptr, nullptr, nullptr, 2) == 0);
  CHECK(e_lo == 0 && e_hi == 3);
  std::vector<uint64_t> row(nv);
  std::vector<uint32_t> col(e_hi - e_lo);
  std::vector<int32_t> w(e_hi - e_lo);
  CHECK(lux_load_partition(path, nv, ne, 0, nv, 1, 4, &e_lo, &e_hi,
                           row.data(), col.data(), w.data(), 2) == 0);
  CHECK(row[2] == 3);
  CHECK(col[0] == 2 && col[1] == 0 && col[2] == 1);
  CHECK(w[0] == 1 && w[1] == 5 && w[2] == 3);
  return 0;
}

static int smoke_rmat() {
  const int scale = 6, ef = 4;
  const uint64_t nv = 1ull << scale, ne = nv * ef;
  std::vector<uint64_t> row(nv);
  std::vector<uint32_t> col(ne), deg(nv);
  CHECK(lux_rmat_csc(scale, ef, 7, 0.57, 0.19, 0.19, row.data(),
                     col.data(), deg.data()) == 0);
  CHECK(row[nv - 1] == ne);
  uint64_t dsum = 0;
  for (uint64_t v = 0; v < nv; v++) dsum += deg[v];
  CHECK(dsum == ne);
  for (uint64_t e = 0; e < ne; e++) CHECK(col[e] < nv);
  return 0;
}

static int smoke_sort() {
  const int64_t n = 4097;  // not a multiple of the radix chunking
  std::vector<uint64_t> keys(n), tmp(n);
  std::vector<int64_t> pay(n), ptmp(n);
  uint64_t x = 0x9e3779b97f4a7c15ull;
  for (int64_t i = 0; i < n; i++) {
    x ^= x << 13;
    x ^= x >> 7;
    x ^= x << 17;
    keys[i] = x % 1000;
    pay[i] = i;
  }
  std::vector<uint64_t> ref(keys);
  void* pays[1] = {pay.data()};
  void* ptmps[1] = {ptmp.data()};
  int32_t psize[1] = {8};
  CHECK(lux_sort_kv_u64(keys.data(), tmp.data(), n, 3, 1, pays,
                        ptmps, psize) == 0);
  for (int64_t i = 1; i < n; i++) CHECK(keys[i - 1] <= keys[i]);
  for (int64_t i = 0; i < n; i++)
    CHECK(ref[(uint64_t)pay[i]] == keys[i]);

  std::vector<int64_t> perm(n);
  CHECK(lux_argsort_u64(ref.data(), n, 3, perm.data()) == 0);
  for (int64_t i = 1; i < n; i++)
    CHECK(ref[perm[i - 1]] <= ref[perm[i]]);
  return 0;
}

static int smoke_reorder() {
  // end-to-end contract of the clustering reorder (reorder.cc): the
  // output is a BIJECTION of [0, nv) and relabeling preserves the
  // degree histogram exactly — checked on the 3-edge smoke graph and
  // on a 2-community R-MAT-free synthetic with an isolated vertex
  // (singleton clusters must still be emitted)
  {
    const uint32_t src3[3] = {2, 0, 1}, dst3[3] = {0, 1, 2};
    uint32_t perm[3];
    for (int hubs = 0; hubs <= 1; hubs++) {
      CHECK(lux_reorder_cluster(3, 3, src3, dst3, hubs, perm) == 0);
      uint32_t seen = 0;
      for (int i = 0; i < 3; i++) {
        CHECK(perm[i] < 3);
        seen |= 1u << perm[i];
      }
      CHECK(seen == 7);
    }
  }
  const uint32_t nv = 9;  // two triangles + a bridge + isolated v8
  const uint32_t src9[7] = {0, 1, 2, 4, 5, 6, 2};
  const uint32_t dst9[7] = {1, 2, 0, 5, 6, 4, 4};
  uint32_t perm[nv];
  // every mode (CM, hub-first, LPA communities) emits a bijection
  for (int mode = 0; mode <= 2; mode++) {
    CHECK(lux_reorder_cluster(nv, 7, src9, dst9, mode, perm) == 0);
    std::vector<uint32_t> mh(nv, 0);
    for (uint32_t i = 0; i < nv; i++) {
      CHECK(perm[i] < nv);
      mh[perm[i]]++;
    }
    for (uint32_t v = 0; v < nv; v++) CHECK(mh[v] == 1);
  }
  CHECK(lux_reorder_cluster(nv, 7, src9, dst9, 1, perm) == 0);
  std::vector<uint32_t> hits(nv, 0);
  for (uint32_t i = 0; i < nv; i++) {
    CHECK(perm[i] < nv);
    hits[perm[i]]++;
  }
  for (uint32_t v = 0; v < nv; v++) CHECK(hits[v] == 1);  // bijection
  // degree histogram preserved under the relabel: deg_new[i] must be
  // deg_old[perm[i]] for every slot, so the multiset is invariant
  std::vector<uint32_t> deg_old(nv, 0), deg_new(nv, 0), rank(nv);
  for (uint32_t i = 0; i < nv; i++) rank[perm[i]] = i;
  for (int e = 0; e < 7; e++) {
    deg_old[src9[e]]++;
    deg_old[dst9[e]]++;
    deg_new[rank[src9[e]]]++;
    deg_new[rank[dst9[e]]]++;
  }
  for (uint32_t i = 0; i < nv; i++)
    CHECK(deg_new[i] == deg_old[perm[i]]);
  // out-of-range edge, missing output, unknown mode: typed refusals
  const uint32_t bad_src[1] = {99}, bad_dst[1] = {0};
  CHECK(lux_reorder_cluster(nv, 1, bad_src, bad_dst, 0, perm) == -2);
  CHECK(lux_reorder_cluster(nv, 7, src9, dst9, 0, nullptr) == -1);
  CHECK(lux_reorder_cluster(nv, 7, src9, dst9, 3, perm) == -4);
  return 0;
}

int main(int argc, char** argv) {
  if (argc != 2) {
    std::fprintf(stderr, "usage: sanitize_driver SMOKE.lux\n");
    return 2;
  }
  if (smoke_loader(argv[1])) return 1;
  if (smoke_rmat()) return 1;
  if (smoke_sort()) return 1;
  if (smoke_reorder()) return 1;
  std::printf("sanitize_driver OK\n");
  return 0;
}
