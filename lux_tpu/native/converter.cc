// lux_converter — text edge list -> .lux binary CSC.
//
// Native equivalent of the framework's Python converter
// (lux_tpu/convert.py) for billion-edge inputs; produces byte-identical
// files.  Same on-disk format as the reference tool
// (reference tools/converter.cc:108-124, README.md:55-79):
//   nv u32 | ne u64 | row_ptrs u64[nv] (END offsets) |
//   col_idx u32[ne] (sources, dst-sorted) | [weights i32[ne]] |
//   degrees u32[nv]
//
// Design (not a translation of the reference): edges are packed into
// one u64 per edge (dst in the high word) so the sort is a flat
// primitive-key sort, weighted edges carry their payload through a
// parallel index sort, and all IO is buffered streaming.
//
// Usage: lux_converter -nv N -ne M -input edges.txt -output g.lux
//        [-weighted]

#include <algorithm>
#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <numeric>
#include <string>
#include <vector>

namespace {

struct Args {
  uint32_t nv = 0;
  uint64_t ne = 0;
  std::string input, output;
  bool weighted = false;
};

[[noreturn]] void usage(const char* msg) {
  std::fprintf(stderr,
               "error: %s\n"
               "usage: lux_converter -nv N -ne M -input edges.txt "
               "-output g.lux [-weighted]\n",
               msg);
  std::exit(2);
}

Args parse(int argc, char** argv) {
  Args a;
  for (int i = 1; i < argc; i++) {
    std::string f = argv[i];
    auto next = [&]() -> const char* {
      if (i + 1 >= argc) usage(("missing value for " + f).c_str());
      return argv[++i];
    };
    if (f == "-nv") a.nv = std::strtoul(next(), nullptr, 10);
    else if (f == "-ne") a.ne = std::strtoull(next(), nullptr, 10);
    else if (f == "-input") a.input = next();
    else if (f == "-output") a.output = next();
    else if (f == "-weighted") a.weighted = true;
    else usage(("unknown flag " + f).c_str());
  }
  if (!a.nv || a.input.empty() || a.output.empty())
    usage("-nv, -input and -output are required");
  return a;
}

void write_all(FILE* f, const void* p, size_t n) {
  if (std::fwrite(p, 1, n, f) != n) {
    std::perror("write");
    std::exit(1);
  }
}

}  // namespace

int main(int argc, char** argv) {
  Args args = parse(argc, argv);

  FILE* fin = std::fopen(args.input.c_str(), "r");
  if (!fin) { std::perror(args.input.c_str()); return 1; }

  // dst in the high 32 bits makes sort order = (dst, src): stable
  // per-destination source order matches the Python converter's
  // stable argsort by dst.
  std::vector<uint64_t> keys;
  std::vector<int32_t> weights;
  if (args.ne) keys.reserve(args.ne);
  std::vector<uint32_t> degrees(args.nv, 0);

  uint64_t lineno = 0;
  long src, dst, w;
  while (true) {
    int got = args.weighted ? std::fscanf(fin, "%ld %ld %ld", &src, &dst, &w)
                            : std::fscanf(fin, "%ld %ld", &src, &dst);
    if (got == EOF) break;
    if (got != (args.weighted ? 3 : 2)) {
      std::fprintf(stderr, "parse error near edge %" PRIu64 "\n", lineno);
      return 1;
    }
    if (src < 0 || dst < 0 || (uint64_t)src >= args.nv ||
        (uint64_t)dst >= args.nv) {
      std::fprintf(stderr, "edge %" PRIu64 " endpoint out of range\n",
                   lineno);
      return 1;
    }
    keys.push_back(((uint64_t)dst << 32) | (uint32_t)src);
    if (args.weighted) weights.push_back((int32_t)w);
    degrees[src]++;
    lineno++;
  }
  std::fclose(fin);
  uint64_t ne = keys.size();
  if (args.ne && args.ne != ne)
    std::fprintf(stderr, "warning: -ne %" PRIu64 " but read %" PRIu64
                 " edges\n", args.ne, ne);

  std::vector<uint32_t> worder;
  if (args.weighted) {
    // Sort an index permutation so weights follow their edges; stable
    // to keep input order within (dst, src) ties.
    worder.resize(ne);
    std::iota(worder.begin(), worder.end(), 0u);
    std::stable_sort(worder.begin(), worder.end(),
                     [&](uint32_t x, uint32_t y) { return keys[x] < keys[y]; });
    std::vector<uint64_t> sorted(ne);
    for (uint64_t e = 0; e < ne; e++) sorted[e] = keys[worder[e]];
    keys.swap(sorted);
  } else {
    std::sort(keys.begin(), keys.end());
  }

  FILE* fout = std::fopen(args.output.c_str(), "wb");
  if (!fout) { std::perror(args.output.c_str()); return 1; }
  write_all(fout, &args.nv, sizeof(uint32_t));
  write_all(fout, &ne, sizeof(uint64_t));

  // END offsets per destination, streamed in chunks.
  {
    std::vector<uint64_t> row_ptrs(args.nv);
    uint64_t e = 0;
    for (uint32_t v = 0; v < args.nv; v++) {
      while (e < ne && (keys[e] >> 32) == v) e++;
      row_ptrs[v] = e;
    }
    write_all(fout, row_ptrs.data(), sizeof(uint64_t) * args.nv);
  }
  {
    std::vector<uint32_t> col(1 << 20);
    uint64_t e = 0;
    while (e < ne) {
      size_t chunk = std::min<uint64_t>(col.size(), ne - e);
      for (size_t i = 0; i < chunk; i++)
        col[i] = (uint32_t)(keys[e + i] & 0xffffffffu);
      write_all(fout, col.data(), sizeof(uint32_t) * chunk);
      e += chunk;
    }
  }
  if (args.weighted) {
    std::vector<int32_t> wsorted(ne);
    for (uint64_t e = 0; e < ne; e++) wsorted[e] = weights[worder[e]];
    write_all(fout, wsorted.data(), sizeof(int32_t) * ne);
  }
  write_all(fout, degrees.data(), sizeof(uint32_t) * args.nv);
  std::fclose(fout);

  std::fprintf(stderr, "wrote %s: nv=%u ne=%" PRIu64 "%s\n",
               args.output.c_str(), args.nv, ne,
               args.weighted ? " (weighted)" : "");
  return 0;
}
