// In-memory R-MAT graph builder (generation + dst-sorted CSC) for
// liblux_native.so.
//
// The framework's benchmark graphs are R-MAT (the reference's RMAT27
// family, reference README.md:86); generating tens of millions of
// edges plus the (dst, src) sort dominates benchmark setup in numpy
// (~90 s at scale 21), so this native path does the whole
// generate+sort+CSC build in C++ — the same role the reference gives
// its native tools for billion-edge inputs (SURVEY.md §2.4).
//
// RNG: splitmix64 (deterministic per seed; a different stream than the
// numpy generator, so graphs match in distribution, not bit-for-bit).

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {

struct SplitMix64 {
  uint64_t s;
  explicit SplitMix64(uint64_t seed) : s(seed) {}
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ull);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
    return z ^ (z >> 31);
  }
  // uniform double in [0, 1)
  double uniform() { return (next() >> 11) * 0x1.0p-53; }
  // uniform integer in [0, n)
  uint64_t below(uint64_t n) { return next() % n; }
};

}  // namespace

extern "C" int lux_rmat_csc(
    int scale, int edge_factor, uint64_t seed,
    double pa, double pb, double pc,
    uint64_t* row_ptrs /* [nv] END offsets */,
    uint32_t* col_idx /* [ne] sources, dst-sorted */,
    uint32_t* degrees /* [nv] out-degrees */) {
  if (scale <= 0 || scale > 31 || edge_factor <= 0) return 1;
  if (!(pa > 0.0) || !(pb >= 0.0) || !(pc >= 0.0) ||
      pa + pb + pc > 1.0)
    return 2;
  const uint64_t nv = 1ull << scale;
  const uint64_t ne = nv * (uint64_t)edge_factor;
  SplitMix64 rng(seed * 0x2545f4914f6cdd1dull + 0x9e3779b97f4a7c15ull);

  // vertex id scramble so the R-MAT skew is not correlated with id
  // order (mirrors the permutation in lux_tpu/convert.py rmat_edges)
  std::vector<uint32_t> perm(nv);
  for (uint64_t v = 0; v < nv; v++) perm[v] = (uint32_t)v;
  for (uint64_t v = nv - 1; v > 0; v--)
    std::swap(perm[v], perm[rng.below(v + 1)]);

  // one u64 key per edge, dst in the high word => flat sort gives the
  // (dst, src) canonical order (same trick as converter.cc)
  std::vector<uint64_t> keys(ne);
  const double ab = pa + pb, abc = pa + pb + pc;
  for (uint64_t e = 0; e < ne; e++) {
    uint64_t src = 0, dst = 0;
    for (int bit = 0; bit < scale; bit++) {
      double r = rng.uniform();
      uint64_t sb = r >= ab ? 1 : 0;                    // quadrants c,d
      uint64_t db = ((r >= pa && r < ab) || r >= abc) ? 1 : 0;
      src = (src << 1) | sb;
      dst = (dst << 1) | db;
    }
    keys[e] = ((uint64_t)perm[dst] << 32) | perm[src];
  }
  std::sort(keys.begin(), keys.end());

  for (uint64_t v = 0; v < nv; v++) degrees[v] = 0;
  for (uint64_t e = 0; e < ne; e++) {
    col_idx[e] = (uint32_t)(keys[e] & 0xffffffffu);
    degrees[col_idx[e]]++;
  }
  uint64_t e = 0;
  for (uint64_t v = 0; v < nv; v++) {
    while (e < ne && (keys[e] >> 32) == v) e++;
    row_ptrs[v] = e;
  }
  return 0;
}
