"""ctypes bindings for the native converter/loader, with auto-build.

The reference's host-side native components are its converter tool and
its per-partition file load tasks (SURVEY.md §2.4); here they are a C++
CLI (converter.cc) and a pthread pread loader (loader.cc).  Python
falls back to the mmap path in lux_tpu.format when the library is not
built or the platform has no toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "build")
_LIB = os.path.join(_BUILD, "liblux_native.so")
CONVERTER = os.path.join(_BUILD, "lux_converter")

_lib = None


def ensure_built(quiet: bool = True) -> bool:
    """Build the native tools if missing.  Returns availability."""
    if os.path.exists(_LIB) and os.path.exists(CONVERTER):
        return True
    try:
        subprocess.run(["make", "-C", _DIR],
                       check=True,
                       capture_output=quiet)
    except (OSError, subprocess.CalledProcessError):
        return False
    return os.path.exists(_LIB) and os.path.exists(CONVERTER)


def _rebuild() -> bool:
    """Force a rebuild (stale .so from before a source was added)."""
    try:
        subprocess.run(["make", "-C", _DIR, "clean"], check=True,
                       capture_output=True)
        subprocess.run(["make", "-C", _DIR], check=True,
                       capture_output=True)
    except (OSError, subprocess.CalledProcessError):
        return False
    return os.path.exists(_LIB)


def _load_lib():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB) and not ensure_built():
        raise OSError("native library unavailable (no toolchain?)")
    lib = ctypes.CDLL(_LIB)
    try:
        _bind(lib)
    except AttributeError:
        # stale build missing a newer symbol: rebuild once.  dlopen
        # caches by path, so the old handle must be closed before the
        # rebuilt library can be mapped.
        import _ctypes
        _ctypes.dlclose(lib._handle)
        if not _rebuild():
            raise OSError("native library stale and rebuild failed")
        lib = ctypes.CDLL(_LIB)
        _bind(lib)
    _lib = lib
    return lib


def _bind(lib):
    lib.lux_read_header.restype = ctypes.c_int
    lib.lux_read_header.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint64)]
    lib.lux_load_partition.restype = ctypes.c_int
    lib.lux_load_partition.argtypes = [
        ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint64,
        ctypes.c_uint32, ctypes.c_uint32, ctypes.c_int, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int]
    lib.lux_count_degrees.restype = ctypes.c_int
    lib.lux_count_degrees.argtypes = [
        ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint64,
        ctypes.c_void_p, ctypes.c_int]
    lib.lux_rmat_csc.restype = ctypes.c_int
    lib.lux_rmat_csc.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
        ctypes.c_double, ctypes.c_double, ctypes.c_double,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.lux_argsort_u64.restype = ctypes.c_int
    lib.lux_argsort_u64.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int, ctypes.c_void_p]
    lib.lux_sort_kv_u64.restype = ctypes.c_int
    lib.lux_sort_kv_u64.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
        ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int32)]
    lib.lux_reorder_cluster.restype = ctypes.c_int
    lib.lux_reorder_cluster.argtypes = [
        ctypes.c_uint32, ctypes.c_uint64, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p]


def available() -> bool:
    try:
        _load_lib()
        return True
    except OSError:
        return False


def _check(rc: int, what: str):
    if rc != 0:
        raise OSError(f"{what} failed with native error {rc} "
                      f"({os.strerror(-rc) if rc < 0 else rc})")


def read_header(path: str) -> tuple[int, int]:
    lib = _load_lib()
    nv = ctypes.c_uint32()
    ne = ctypes.c_uint64()
    _check(lib.lux_read_header(path.encode(), ctypes.byref(nv),
                               ctypes.byref(ne)), "read_header")
    return nv.value, ne.value


def load_partition(path: str, nv: int, ne: int, v0: int, v1: int,
                   weighted: bool = False, weight_dtype=np.int32,
                   threads: int = 8):
    """Load vertex range [v0, v1): returns (row_ptrs u64[v1-v0] END
    offsets, col_idx u32[e_hi-e_lo], weights|None, e_lo)."""
    lib = _load_lib()
    e_lo = ctypes.c_uint64()
    e_hi = ctypes.c_uint64()
    # size query
    _check(lib.lux_load_partition(path.encode(), nv, ne, v0, v1,
                                  int(weighted), 4, ctypes.byref(e_lo),
                                  ctypes.byref(e_hi), None, None, None,
                                  threads), "load_partition(size)")
    n_edges = e_hi.value - e_lo.value
    rows = np.empty(v1 - v0, dtype=np.uint64)
    cols = np.empty(n_edges, dtype=np.uint32)
    wdt = np.dtype(weight_dtype)
    weights = np.empty(n_edges, dtype=wdt) if weighted else None
    _check(lib.lux_load_partition(
        path.encode(), nv, ne, v0, v1, int(weighted), wdt.itemsize,
        ctypes.byref(e_lo), ctypes.byref(e_hi),
        rows.ctypes.data_as(ctypes.c_void_p),
        cols.ctypes.data_as(ctypes.c_void_p),
        weights.ctypes.data_as(ctypes.c_void_p) if weighted else None,
        threads), "load_partition")
    return rows, cols, weights, e_lo.value


def count_degrees(path: str, nv: int, ne: int, threads: int = 8):
    lib = _load_lib()
    deg = np.zeros(nv, dtype=np.uint32)
    _check(lib.lux_count_degrees(path.encode(), nv, ne,
                                 deg.ctypes.data_as(ctypes.c_void_p),
                                 threads), "count_degrees")
    return deg


def rmat_csc(scale: int, edge_factor: int = 16, seed: int = 0,
             a: float = 0.57, b: float = 0.19, c: float = 0.19):
    """Generate an R-MAT graph directly as dst-sorted CSC in C++.

    Returns (row_ptrs u64[nv] END offsets, col_idx u32[ne],
    out_degrees u32[nv]).  Same distribution family as
    lux_tpu.convert.rmat_edges but a different RNG stream, so graphs
    are NOT bit-identical to the numpy generator's.
    """
    lib = _load_lib()
    nv = 1 << scale
    ne = nv * edge_factor
    row_ptrs = np.empty(nv, dtype=np.uint64)
    col_idx = np.empty(ne, dtype=np.uint32)
    degrees = np.empty(nv, dtype=np.uint32)
    _check(lib.lux_rmat_csc(
        scale, edge_factor, seed, a, b, c,
        row_ptrs.ctypes.data_as(ctypes.c_void_p),
        col_idx.ctypes.data_as(ctypes.c_void_p),
        degrees.ctypes.data_as(ctypes.c_void_p)), "rmat_csc")
    return row_ptrs, col_idx, degrees


def argsort_u64(keys, threads: int | None = None):
    """Stable parallel radix argsort of non-negative int64/uint64 keys
    (sort.cc).  Single-core hosts run at numpy-radix speed; pod hosts
    scale with cores (PERF_NOTES round-3 #4).  Returns int64 perm."""
    keys = np.ascontiguousarray(keys)
    if keys.dtype == np.int64:
        if keys.size and int(keys.min()) < 0:
            raise ValueError("argsort_u64 needs non-negative keys")
        keys = keys.view(np.uint64)
    elif keys.dtype != np.uint64:
        raise ValueError(f"argsort_u64: unsupported dtype {keys.dtype}")
    if threads is None:
        threads = min(16, os.cpu_count() or 1)
    out = np.empty(keys.size, np.int64)
    lib = _load_lib()
    _check(lib.lux_argsort_u64(
        keys.ctypes.data_as(ctypes.c_void_p), keys.size, int(threads),
        out.ctypes.data_as(ctypes.c_void_p)), "lux_argsort_u64")
    return out


def sort_kv(keys, payloads=(), threads: int | None = None) -> None:
    """Fused stable radix sort IN PLACE: sorts non-negative int64/
    uint64 ``keys`` and carries each array in ``payloads`` (same
    length; element size 1/2/4/8) through the same permutation
    (sort.cc lux_sort_kv_u64).

    This replaces the argsort + one-random-gather-per-array pattern of
    the billion-edge host-prep pipelines (pair_relabel's histogram,
    edges_to_csc, OwnerLayout.build — PERF_NOTES round-4 host prep):
    every radix pass reads sequentially and writes 256 bucketed
    streams, where an argsort pays random key reads per pass and the
    callers then pay one random gather PER payload.  Falls back to
    numpy argsort + in-place takes when the native library is
    unavailable."""
    keys = _as_u64_inplace(keys)
    n = keys.size
    if len(payloads) > 4:            # sort.cc kMaxPay; keep the numpy
        raise ValueError(            # fallback behaviorally identical
            f"sort_kv supports at most 4 payloads, got {len(payloads)}")
    for p in payloads:
        if not isinstance(p, np.ndarray) or not p.flags.c_contiguous:
            raise ValueError("sort_kv payloads must be contiguous "
                             "numpy arrays")
        if p.shape != (n,):
            raise ValueError("sort_kv payloads must match keys' length")
        if p.dtype.itemsize not in (1, 2, 4, 8):
            raise ValueError(f"unsupported payload itemsize "
                             f"{p.dtype.itemsize}")
    if n == 0:
        return
    if not available():
        order = np.argsort(keys, kind="stable")
        keys[:] = keys[order]
        for p in payloads:
            p[:] = p[order]
        return
    if threads is None:
        threads = min(16, os.cpu_count() or 1)
    lib = _load_lib()
    key_tmp = np.empty(n, np.uint64)
    pay_tmp = [np.empty(n, p.dtype) for p in payloads]
    npay = len(payloads)
    PtrArr = ctypes.c_void_p * max(1, npay)
    pays = PtrArr(*[p.ctypes.data for p in payloads])
    tmps = PtrArr(*[p.ctypes.data for p in pay_tmp])
    sizes = (ctypes.c_int32 * max(1, npay))(
        *[p.dtype.itemsize for p in payloads])
    _check(lib.lux_sort_kv_u64(
        keys.ctypes.data_as(ctypes.c_void_p),
        key_tmp.ctypes.data_as(ctypes.c_void_p),
        n, int(threads), npay, pays, tmps, sizes), "lux_sort_kv_u64")


REORDER_MODES = {"cm": 0, "hubs": 1, "communities": 2}


def reorder_cluster(src, dst, nv: int,
                    mode: str | int = "hubs") -> np.ndarray:
    """Clustering vertex reorder (reorder.cc): ``"cm"`` = classic
    ascending-degree Cuthill-McKee BFS, ``"hubs"`` = hub-first BFS
    (descending degree), ``"communities"`` = label-propagation
    community grouping (the Rabbit-order move — BFS leaks across
    clusters; a few LPA rounds recover them) — the page-locality
    preprocessing passes the paged gather needs (ops/pagegather.py;
    sanitize-covered end-to-end: bijection + degree histogram).

    Returns uint32 ``perm`` with ``perm[new] = old`` (the
    degree_relabel direction).  Falls back to a NumPy implementation
    when the native library is unavailable — same contract, slower
    host prep."""
    src = np.ascontiguousarray(src, np.uint32)
    dst = np.ascontiguousarray(dst, np.uint32)
    m = REORDER_MODES.get(mode, mode) if isinstance(mode, str) \
        else int(mode)
    if m not in (0, 1, 2):
        raise ValueError(f"unknown reorder mode {mode!r} (one of "
                         f"{', '.join(REORDER_MODES)})")
    if src.shape != dst.shape or src.ndim != 1:
        raise ValueError("reorder_cluster needs matching 1-D src/dst")
    if src.size and (int(src.max()) >= nv or int(dst.max()) >= nv):
        raise ValueError(f"edge endpoint outside [0, {nv})")
    if not available():
        return _reorder_cluster_numpy(src, dst, nv, m)
    perm = np.empty(nv, np.uint32)
    lib = _load_lib()
    _check(lib.lux_reorder_cluster(
        nv, src.size,
        src.ctypes.data_as(ctypes.c_void_p),
        dst.ctypes.data_as(ctypes.c_void_p),
        m,
        perm.ctypes.data_as(ctypes.c_void_p)), "lux_reorder_cluster")
    return perm


def _reorder_cluster_numpy(src, dst, nv: int, mode: int) -> np.ndarray:
    """Pure-NumPy fallback of reorder.cc — identical contract
    (bijection, perm[new] = old), used when the toolchain is missing;
    the C++ path is the production one."""
    from collections import deque

    deg = (np.bincount(src, minlength=nv).astype(np.int64)
           + np.bincount(dst, minlength=nv))
    u = np.concatenate([src, dst]).astype(np.int64)
    v = np.concatenate([dst, src]).astype(np.int64)
    order = np.argsort(u, kind="stable")
    v = v[order]
    off = np.zeros(nv + 1, np.int64)
    np.add.at(off, u + 1, 1)
    off = np.cumsum(off)
    u = u[order]
    if mode == 2:
        # synchronous sort-based label propagation (the C++ pass is
        # async; both converge to community groupings, not to
        # bit-identical orders — the hill-climb scores by measured
        # fill either way)
        labels = np.arange(nv, dtype=np.int64)
        for _ in range(8):
            key = u * np.int64(nv) + labels[v]
            ks = np.sort(key, kind="stable")
            new = np.ones(len(ks), bool)
            new[1:] = ks[1:] != ks[:-1]
            b = np.nonzero(new)[0]
            cnt = np.diff(np.concatenate((b, [len(ks)])))
            uu = ks[b] // nv
            lab = ks[b] % nv
            o2 = np.lexsort((lab, -cnt, uu))
            first = np.ones(len(o2), bool)
            first[1:] = uu[o2][1:] != uu[o2][:-1]
            newlab = labels.copy()
            newlab[uu[o2][first]] = lab[o2][first]
            if np.array_equal(newlab, labels):
                break
            labels = newlab
        # (community by first touch in degree-major order, degree
        # desc, id)
        sweep = np.argsort(-deg, kind="stable")
        rank = np.empty(nv, np.int64)
        rank[sweep] = np.arange(nv)
        comm_rank = np.full(nv, nv, np.int64)
        np.minimum.at(comm_rank, labels, rank)
        return sweep[np.argsort(comm_rank[labels[sweep]],
                                kind="stable")].astype(np.uint32)
    sign = -1 if mode == 1 else 1
    seeds = np.argsort(sign * deg, kind="stable")
    visited = np.zeros(nv, bool)
    out = np.empty(nv, np.uint32)
    pos = 0
    dq = deque()
    for s in seeds:
        if visited[s]:
            continue
        visited[s] = True
        dq.append(int(s))
        while dq:
            x = dq.popleft()
            out[pos] = x
            pos += 1
            nb = v[off[x]:off[x + 1]]
            nb = np.unique(nb[~visited[nb]])
            if nb.size:
                nb = nb[np.argsort(sign * deg[nb], kind="stable")]
                visited[nb] = True
                dq.extend(int(n) for n in nb)
    assert pos == nv
    return out


def _as_u64_inplace(keys):
    """Validate keys for the in-place native sort: contiguous int64
    (non-negative) or uint64; returns a uint64 VIEW of the same
    memory."""
    if not isinstance(keys, np.ndarray) or not keys.flags.c_contiguous:
        raise ValueError("sort_kv keys must be a contiguous numpy array")
    if keys.dtype == np.int64:
        if keys.size and int(keys.min()) < 0:
            raise ValueError("sort_kv needs non-negative keys")
        return keys.view(np.uint64)
    if keys.dtype != np.uint64:
        raise ValueError(f"sort_kv: unsupported key dtype {keys.dtype}")
    return keys


