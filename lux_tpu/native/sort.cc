// Parallel radix argsort for the host prep pipeline.
//
// The framework's big host-side costs at billion-edge scale are int64
// key argsorts (pair_relabel's pair histogram, edges_to_csc's
// (dst, src) order, OwnerLayout's (src-part, dst-tile) order —
// PERF_NOTES round-3 #4); numpy's radix sort is single-threaded.
// This is a pthread LSD radix argsort over 8-bit digits: per pass,
// per-thread histograms over a block of the input, an exclusive scan
// over (digit, thread) for stable placement, then a scatter pass.
// One CPU runs at numpy-comparable speed; pod hosts with many cores
// scale near-linearly (the reference's converter leans on big host
// RAM + cores the same way, reference tools/converter.cc:85-98).
//
// C ABI (ctypes): lux_argsort_u64(keys, n, threads, perm_out).
// perm_out must hold n int64; keys are NOT modified.

#include <cstdint>
#include <cstring>
#include <memory>
#include <pthread.h>
#include <vector>

namespace {

struct PassArgs {
  const uint64_t* keys;       // key of ORIGINAL index i
  const int64_t* src;         // current permutation (input order)
  int64_t* dst;               // output permutation
  int64_t lo, hi;             // this thread's slice of src
  int shift;
  int64_t* hist;              // [256] this thread's digit histogram
  int64_t* offs;              // [256] this thread's placement offsets
};

void* hist_pass(void* p) {
  auto* a = static_cast<PassArgs*>(p);
  std::memset(a->hist, 0, 256 * sizeof(int64_t));
  for (int64_t i = a->lo; i < a->hi; i++) {
    a->hist[(a->keys[a->src[i]] >> a->shift) & 0xff]++;
  }
  return nullptr;
}

void* scatter_pass(void* p) {
  auto* a = static_cast<PassArgs*>(p);
  for (int64_t i = a->lo; i < a->hi; i++) {
    int64_t v = a->src[i];
    int d = (a->keys[v] >> a->shift) & 0xff;
    a->dst[a->offs[d]++] = v;
  }
  return nullptr;
}

}  // namespace

extern "C" int lux_argsort_u64(const uint64_t* keys, int64_t n,
                               int threads, int64_t* perm_out) {
  if (n < 0 || threads < 1) return 1;
  if (threads > 256) threads = 256;
  // uninitialized scratch (a vector would zero-fill 8 GB at scale)
  std::unique_ptr<int64_t[]> tmp(new int64_t[n]);
  int64_t* cur = perm_out;
  int64_t* nxt = tmp.get();
  for (int64_t i = 0; i < n; i++) cur[i] = i;

  std::vector<int64_t> hist(static_cast<size_t>(threads) * 256);
  std::vector<int64_t> offs(static_cast<size_t>(threads) * 256);
  std::vector<PassArgs> args(threads);
  std::vector<pthread_t> tid(threads);
  std::vector<char> created(threads, 0);
  int64_t chunk = (n + threads - 1) / threads;

  for (int pass = 0; pass < 8; pass++) {
    int shift = pass * 8;
    for (int t = 0; t < threads; t++) {
      int64_t lo = t * chunk;
      int64_t hi = lo + chunk < n ? lo + chunk : n;
      if (lo > n) lo = n;
      args[t] = PassArgs{keys, cur, nxt, lo, hi, shift,
                         &hist[static_cast<size_t>(t) * 256],
                         &offs[static_cast<size_t>(t) * 256]};
      // run inline on pthread_create failure (EAGAIN on loaded
      // hosts) — joining an uninitialized handle is UB
      if (threads <= 1 || pthread_create(&tid[t], nullptr, hist_pass,
                                         &args[t]) != 0) {
        hist_pass(&args[t]);
        created[t] = false;
      } else {
        created[t] = true;
      }
    }
    for (int t = 0; t < threads; t++)
      if (created[t]) pthread_join(tid[t], nullptr);
    // all keys in one digit bucket => the pass is the identity
    // permutation; skip the scatter (typical keys leave the top
    // bytes zero, halving the passes or better)
    bool trivial = false;
    for (int d = 0; d < 256 && !trivial; d++) {
      int64_t tot = 0;
      for (int t = 0; t < threads; t++)
        tot += hist[static_cast<size_t>(t) * 256 + d];
      if (tot == n) trivial = true;
    }
    if (trivial) continue;
    // exclusive scan in (digit, thread) order => stable placement
    int64_t run = 0;
    for (int d = 0; d < 256; d++) {
      for (int t = 0; t < threads; t++) {
        offs[static_cast<size_t>(t) * 256 + d] = run;
        run += hist[static_cast<size_t>(t) * 256 + d];
      }
    }
    for (int t = 0; t < threads; t++) {
      if (threads <= 1 || pthread_create(&tid[t], nullptr, scatter_pass,
                                         &args[t]) != 0) {
        scatter_pass(&args[t]);
        created[t] = false;
      } else {
        created[t] = true;
      }
    }
    for (int t = 0; t < threads; t++)
      if (created[t]) pthread_join(tid[t], nullptr);
    std::swap(cur, nxt);
  }
  // trivial-pass skips can leave the result in the scratch buffer
  if (cur != perm_out)
    std::memcpy(perm_out, cur, static_cast<size_t>(n) * sizeof(int64_t));
  return 0;
}
