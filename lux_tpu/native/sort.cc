// Parallel radix argsort for the host prep pipeline.
//
// The framework's big host-side costs at billion-edge scale are int64
// key argsorts (pair_relabel's pair histogram, edges_to_csc's
// (dst, src) order, OwnerLayout's (src-part, dst-tile) order —
// PERF_NOTES round-3 #4); numpy's radix sort is single-threaded.
// This is a pthread LSD radix argsort over 8-bit digits: per pass,
// per-thread histograms over a block of the input, an exclusive scan
// over (digit, thread) for stable placement, then a scatter pass.
// One CPU runs at numpy-comparable speed; pod hosts with many cores
// scale near-linearly (the reference's converter leans on big host
// RAM + cores the same way, reference tools/converter.cc:85-98).
//
// C ABI (ctypes): lux_argsort_u64(keys, n, threads, perm_out).
// perm_out must hold n int64; keys are NOT modified.

#include <cstdint>
#include <cstring>
#include <memory>
#include <pthread.h>
#include <vector>

namespace {

struct PassArgs {
  const uint64_t* keys;       // key of ORIGINAL index i
  const int64_t* src;         // current permutation (input order)
  int64_t* dst;               // output permutation
  int64_t lo, hi;             // this thread's slice of src
  int shift;
  int64_t* hist;              // [256] this thread's digit histogram
  int64_t* offs;              // [256] this thread's placement offsets
};

void* hist_pass(void* p) {
  auto* a = static_cast<PassArgs*>(p);
  std::memset(a->hist, 0, 256 * sizeof(int64_t));
  for (int64_t i = a->lo; i < a->hi; i++) {
    a->hist[(a->keys[a->src[i]] >> a->shift) & 0xff]++;
  }
  return nullptr;
}

void* scatter_pass(void* p) {
  auto* a = static_cast<PassArgs*>(p);
  for (int64_t i = a->lo; i < a->hi; i++) {
    int64_t v = a->src[i];
    int d = (a->keys[v] >> a->shift) & 0xff;
    a->dst[a->offs[d]++] = v;
  }
  return nullptr;
}

}  // namespace

// ---------------------------------------------------------------------------
// Fused key+payload radix sort (round 4, PERF_NOTES "host prep").
//
// lux_argsort_u64 permutes an index array and re-reads keys[perm[i]]
// every pass — random reads that made it ~2x SLOWER than numpy at one
// thread; and every caller then pays one random GATHER per payload
// array (key[order], srcl[order], ...).  This entry sorts the keys
// IN PLACE and carries the payload arrays through the same stable
// permutation, so every pass is sequential reads + 256 bucketed write
// streams and the after-the-sort gathers disappear entirely.  One
// histogram scan up front computes all eight digit histograms at
// once; only non-trivial digits get a scatter pass (typical keys are
// bounded far under 2^64 — (src-part)*G+tile keys fit ~26 bits, so
// only 4 of 8 passes move data).
//
// The same host-prep role as the reference converter's big in-memory
// sort (reference tools/converter.cc:85-98), generalized to the
// relabel/owner pipelines.
//
// C ABI (ctypes):
//   lux_sort_kv_u64(keys, key_tmp, n, threads,
//                   n_pay, pay, pay_tmp, pay_size)
// keys/key_tmp: n u64 (key_tmp uninitialized scratch); pay/pay_tmp:
// n_pay pointers to payload arrays and equally-sized scratch;
// pay_size: per-payload element size (1/2/4/8).  All arrays are
// modified; on return keys and payloads hold the sorted order.

namespace {

constexpr int kMaxPay = 4;

struct KvPass {
  uint64_t* key_in;
  uint64_t* key_out;
  char* pay_in[kMaxPay];
  char* pay_out[kMaxPay];
  int n_pay;
  int pay_size[kMaxPay];
  int64_t lo, hi;
  int shift;
  int64_t* offs;              // [256] this thread's placement offsets
};

struct HistArgs {
  const uint64_t* keys;
  int64_t lo, hi;
  int shift;
  int64_t* hist;              // [256]
  uint64_t maxk;
};

void* kv_hist(void* p) {
  auto* a = static_cast<HistArgs*>(p);
  std::memset(a->hist, 0, 256 * sizeof(int64_t));
  for (int64_t i = a->lo; i < a->hi; i++)
    a->hist[(a->keys[i] >> a->shift) & 0xff]++;
  return nullptr;
}

void* kv_max(void* p) {
  auto* a = static_cast<HistArgs*>(p);
  uint64_t m = 0;
  for (int64_t i = a->lo; i < a->hi; i++)
    if (a->keys[i] > m) m = a->keys[i];
  a->maxk = m;
  return nullptr;
}

template <typename T>
inline void copy_one(char* dst, const char* src, int64_t di, int64_t si) {
  reinterpret_cast<T*>(dst)[di] =
      reinterpret_cast<const T*>(src)[si];
}

void* kv_scatter(void* p) {
  auto* a = static_cast<KvPass*>(p);
  for (int64_t i = a->lo; i < a->hi; i++) {
    uint64_t k = a->key_in[i];
    int64_t pos = a->offs[(k >> a->shift) & 0xff]++;
    a->key_out[pos] = k;
    for (int j = 0; j < a->n_pay; j++) {
      switch (a->pay_size[j]) {
        case 1: copy_one<uint8_t>(a->pay_out[j], a->pay_in[j], pos, i); break;
        case 2: copy_one<uint16_t>(a->pay_out[j], a->pay_in[j], pos, i); break;
        case 4: copy_one<uint32_t>(a->pay_out[j], a->pay_in[j], pos, i); break;
        default: copy_one<uint64_t>(a->pay_out[j], a->pay_in[j], pos, i);
      }
    }
  }
  return nullptr;
}

}  // namespace

extern "C" int lux_sort_kv_u64(uint64_t* keys, uint64_t* key_tmp,
                               int64_t n, int threads, int n_pay,
                               void** pay, void** pay_tmp,
                               const int32_t* pay_size) {
  if (n < 0 || threads < 1 || n_pay < 0 || n_pay > kMaxPay) return 1;
  for (int j = 0; j < n_pay; j++) {
    int s = pay_size[j];
    if (s != 1 && s != 2 && s != 4 && s != 8) return 2;
  }
  if (n == 0) return 0;
  if (threads > 256) threads = 256;
  int64_t chunk = (n + threads - 1) / threads;

  std::vector<HistArgs> ha(threads);
  std::vector<pthread_t> tid(threads);
  std::vector<char> created(threads, 0);

  auto run_threads = [&](void* (*fn)(void*), auto* argv) {
    for (int t = 0; t < threads; t++) {
      if (threads <= 1 || pthread_create(&tid[t], nullptr, fn,
                                         &argv[t]) != 0) {
        fn(&argv[t]);
        created[t] = false;
      } else {
        created[t] = true;
      }
    }
    for (int t = 0; t < threads; t++)
      if (created[t]) pthread_join(tid[t], nullptr);
  };

  // pass count from the max key: high zero bytes never need a pass
  // (the common case — tile/part keys are bounded far under 2^64)
  uint64_t maxk = 0;
  {
    for (int t = 0; t < threads; t++) {
      int64_t lo = t * chunk;
      int64_t hi = lo + chunk < n ? lo + chunk : n;
      if (lo > n) lo = n;
      ha[t] = HistArgs{keys, lo, hi, 0, nullptr, 0};
    }
    run_threads(kv_max, ha.data());
    for (int t = 0; t < threads; t++)
      if (ha[t].maxk > maxk) maxk = ha[t].maxk;
  }
  int npass = 0;
  while (npass < 8 && (maxk >> (npass * 8)) != 0) npass++;

  uint64_t* kcur = keys;
  uint64_t* knxt = key_tmp;
  std::vector<char*> pcur(n_pay), pnxt(n_pay);
  for (int j = 0; j < n_pay; j++) {
    pcur[j] = static_cast<char*>(pay[j]);
    pnxt[j] = static_cast<char*>(pay_tmp[j]);
  }

  std::vector<int64_t> hist(static_cast<size_t>(threads) * 256);
  std::vector<int64_t> offs(static_cast<size_t>(threads) * 256);
  std::vector<KvPass> args(threads);

  for (int pass = 0; pass < npass; pass++) {
    int shift = pass * 8;
    // per-thread digit histogram of the CURRENT order (key-only scan)
    for (int t = 0; t < threads; t++) {
      int64_t lo = t * chunk;
      int64_t hi = lo + chunk < n ? lo + chunk : n;
      if (lo > n) lo = n;
      ha[t] = HistArgs{kcur, lo, hi, shift,
                       &hist[static_cast<size_t>(t) * 256], 0};
    }
    run_threads(kv_hist, ha.data());
    // all keys in one digit bucket => identity pass; skip the scatter
    bool trivial = false;
    for (int d = 0; d < 256 && !trivial; d++) {
      int64_t tot = 0;
      for (int t = 0; t < threads; t++)
        tot += hist[static_cast<size_t>(t) * 256 + d];
      if (tot == n) trivial = true;
    }
    if (trivial) continue;
    // exclusive scan in (digit, thread) order => stable placement
    int64_t run = 0;
    for (int d = 0; d < 256; d++) {
      for (int t = 0; t < threads; t++) {
        offs[static_cast<size_t>(t) * 256 + d] = run;
        run += hist[static_cast<size_t>(t) * 256 + d];
      }
    }
    for (int t = 0; t < threads; t++) {
      int64_t lo = t * chunk;
      int64_t hi = lo + chunk < n ? lo + chunk : n;
      if (lo > n) lo = n;
      args[t] = KvPass{};
      args[t].key_in = kcur;
      args[t].key_out = knxt;
      args[t].n_pay = n_pay;
      args[t].lo = lo;
      args[t].hi = hi;
      args[t].shift = shift;
      args[t].offs = &offs[static_cast<size_t>(t) * 256];
      for (int j = 0; j < n_pay; j++) {
        args[t].pay_in[j] = pcur[j];
        args[t].pay_out[j] = pnxt[j];
        args[t].pay_size[j] = pay_size[j];
      }
    }
    run_threads(kv_scatter, args.data());
    std::swap(kcur, knxt);
    for (int j = 0; j < n_pay; j++) std::swap(pcur[j], pnxt[j]);
  }

  // an odd number of scatter passes leaves the result in the scratch
  if (kcur != keys) {
    std::memcpy(keys, kcur, static_cast<size_t>(n) * sizeof(uint64_t));
    for (int j = 0; j < n_pay; j++)
      std::memcpy(pay[j], pcur[j],
                  static_cast<size_t>(n) * pay_size[j]);
  }
  return 0;
}

extern "C" int lux_argsort_u64(const uint64_t* keys, int64_t n,
                               int threads, int64_t* perm_out) {
  if (n < 0 || threads < 1) return 1;
  if (threads > 256) threads = 256;
  // uninitialized scratch (a vector would zero-fill 8 GB at scale)
  std::unique_ptr<int64_t[]> tmp(new int64_t[n]);
  int64_t* cur = perm_out;
  int64_t* nxt = tmp.get();
  for (int64_t i = 0; i < n; i++) cur[i] = i;

  std::vector<int64_t> hist(static_cast<size_t>(threads) * 256);
  std::vector<int64_t> offs(static_cast<size_t>(threads) * 256);
  std::vector<PassArgs> args(threads);
  std::vector<pthread_t> tid(threads);
  std::vector<char> created(threads, 0);
  int64_t chunk = (n + threads - 1) / threads;

  for (int pass = 0; pass < 8; pass++) {
    int shift = pass * 8;
    for (int t = 0; t < threads; t++) {
      int64_t lo = t * chunk;
      int64_t hi = lo + chunk < n ? lo + chunk : n;
      if (lo > n) lo = n;
      args[t] = PassArgs{keys, cur, nxt, lo, hi, shift,
                         &hist[static_cast<size_t>(t) * 256],
                         &offs[static_cast<size_t>(t) * 256]};
      // run inline on pthread_create failure (EAGAIN on loaded
      // hosts) — joining an uninitialized handle is UB
      if (threads <= 1 || pthread_create(&tid[t], nullptr, hist_pass,
                                         &args[t]) != 0) {
        hist_pass(&args[t]);
        created[t] = false;
      } else {
        created[t] = true;
      }
    }
    for (int t = 0; t < threads; t++)
      if (created[t]) pthread_join(tid[t], nullptr);
    // all keys in one digit bucket => the pass is the identity
    // permutation; skip the scatter (typical keys leave the top
    // bytes zero, halving the passes or better)
    bool trivial = false;
    for (int d = 0; d < 256 && !trivial; d++) {
      int64_t tot = 0;
      for (int t = 0; t < threads; t++)
        tot += hist[static_cast<size_t>(t) * 256 + d];
      if (tot == n) trivial = true;
    }
    if (trivial) continue;
    // exclusive scan in (digit, thread) order => stable placement
    int64_t run = 0;
    for (int d = 0; d < 256; d++) {
      for (int t = 0; t < threads; t++) {
        offs[static_cast<size_t>(t) * 256 + d] = run;
        run += hist[static_cast<size_t>(t) * 256 + d];
      }
    }
    for (int t = 0; t < threads; t++) {
      if (threads <= 1 || pthread_create(&tid[t], nullptr, scatter_pass,
                                         &args[t]) != 0) {
        scatter_pass(&args[t]);
        created[t] = false;
      } else {
        created[t] = true;
      }
    }
    for (int t = 0; t < threads; t++)
      if (created[t]) pthread_join(tid[t], nullptr);
    std::swap(cur, nxt);
  }
  // trivial-pass skips can leave the result in the scratch buffer
  if (cur != perm_out)
    std::memcpy(perm_out, cur, static_cast<size_t>(n) * sizeof(int64_t));
  return 0;
}
