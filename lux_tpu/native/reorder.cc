// Page-aware clustering vertex reorder (ROADMAP item 1, round 16).
//
// The paged two-level gather (lux_tpu/ops/pagegather.py) delivers
// edges at the modeled ~1.6 ns only when edges sharing a
// (dst tile, src page) actually cluster — and R-MAT under a plain
// degree sort does not (measured fill 6-12 vs break-even 23,
// PERF_NOTES round 15).  This pass manufactures that locality on the
// host, once, like the converter/sort beside it: a Cuthill-McKee
// style clustering BFS (the Rabbit-order/RCM family — Lux itself
// wins by choosing edge layouts matched to its memory hierarchy,
// reference README.md:33-38) that lays each traversed neighborhood
// contiguously, so a 128-vertex destination tile's in-edge sources
// concentrate into few 128-wide state pages.
//
// Three modes are exposed: 0 = classic ascending-degree
// Cuthill-McKee BFS; 1 = hub-first BFS (descending degree), which
// groups the power-law hubs' shared neighborhoods early; 2 = LABEL
// PROPAGATION communities (the Rabbit-order move: a few async LPA
// rounds recover cluster structure BFS leaks across — each vertex
// adopts its neighbors' most frequent label, ties to the smaller —
// then vertices lay out grouped by community, degree-major within).
// The Python hill-climb driver (lux_tpu/reorder.py) scores all of
// them against the plan builder's measured page_fill objective and
// refines the winner.
//
// Output contract: perm_out[new_position] = old_id — the same
// direction as lux_tpu.graph.degree_relabel's perm, and what the
// .perm sidecar stores (lux_tpu/format.py).  The result is always a
// bijection of [0, nv): every vertex is visited exactly once
// (isolated vertices seed their own singleton clusters), checked
// end-to-end by the sanitize driver.
#include <algorithm>
#include <cstdint>
#include <vector>

extern "C" int lux_reorder_cluster(uint32_t nv, uint64_t ne,
                                   const uint32_t* src,
                                   const uint32_t* dst,
                                   int mode,
                                   uint32_t* perm_out) {
  if (perm_out == nullptr || (ne > 0 && (src == nullptr || dst == nullptr)))
    return -1;
  if (mode < 0 || mode > 2) return -4;
  if (nv == 0) return 0;
  const bool hubs_first = mode != 0;

  // undirected degree + adjacency CSR (both directions): the
  // clustering objective is symmetric — a page is good when its
  // vertices SHARE neighborhoods, regardless of edge direction
  std::vector<uint64_t> off(static_cast<size_t>(nv) + 1, 0);
  for (uint64_t e = 0; e < ne; e++) {
    if (src[e] >= nv || dst[e] >= nv) return -2;
    off[src[e] + 1]++;
    off[dst[e] + 1]++;
  }
  for (uint32_t v = 0; v < nv; v++) off[v + 1] += off[v];
  std::vector<uint32_t> adj(2 * ne);
  {
    std::vector<uint64_t> cur(off.begin(), off.end() - 1);
    for (uint64_t e = 0; e < ne; e++) {
      adj[cur[src[e]]++] = dst[e];
      adj[cur[dst[e]]++] = src[e];
    }
  }
  std::vector<uint64_t> deg(nv);
  for (uint32_t v = 0; v < nv; v++) deg[v] = off[v + 1] - off[v];

  if (mode == 2) {
    // label-propagation communities: async sweeps in degree-desc
    // order; each vertex adopts the most frequent label among its
    // neighbors (ties -> smaller label).  Converges in a handful of
    // rounds on clustered graphs; the final order groups vertices by
    // community (communities by first-touch of their final label),
    // degree-major within, so a community's members share state
    // pages — the objective the paged plan bins for.
    std::vector<uint32_t> labels(nv), sweep(nv);
    for (uint32_t v = 0; v < nv; v++) labels[v] = v;
    for (uint32_t v = 0; v < nv; v++) sweep[v] = v;
    std::stable_sort(sweep.begin(), sweep.end(),
                     [&](uint32_t a, uint32_t b) {
                       return deg[a] > deg[b];
                     });
    std::vector<uint32_t> nlab;
    const int kRounds = 8;
    for (int round = 0; round < kRounds; round++) {
      uint64_t changed = 0;
      for (uint32_t v : sweep) {
        if (off[v + 1] == off[v]) continue;
        nlab.clear();
        for (uint64_t i = off[v]; i < off[v + 1]; i++)
          nlab.push_back(labels[adj[i]]);
        std::sort(nlab.begin(), nlab.end());
        uint32_t best = nlab[0], cur = nlab[0];
        uint64_t best_n = 0, cur_n = 0;
        for (uint32_t l : nlab) {
          if (l == cur) {
            cur_n++;
          } else {
            cur = l;
            cur_n = 1;
          }
          if (cur_n > best_n) {
            best_n = cur_n;
            best = cur;
          }
        }
        if (best != labels[v]) {
          labels[v] = best;
          changed++;
        }
      }
      if (changed == 0) break;
    }
    // order: (community by first touch in degree-major sweep,
    // degree desc, id) — stable two-key sort via community rank
    std::vector<uint32_t> comm_rank(nv, 0);
    std::vector<uint8_t> seen(nv, 0);
    uint32_t next_comm = 0;
    for (uint32_t v : sweep) {
      uint32_t l = labels[v];
      if (!seen[l]) {
        seen[l] = 1;
        comm_rank[l] = next_comm++;
      }
    }
    std::vector<uint32_t> order(sweep);  // already degree-desc
    std::stable_sort(order.begin(), order.end(),
                     [&](uint32_t a, uint32_t b) {
                       return comm_rank[labels[a]]
                            < comm_rank[labels[b]];
                     });
    for (uint32_t i = 0; i < nv; i++) perm_out[i] = order[i];
    return 0;
  }

  // seed order: stable degree sort (descending for hub-first)
  std::vector<uint32_t> seeds(nv);
  for (uint32_t v = 0; v < nv; v++) seeds[v] = v;
  std::stable_sort(seeds.begin(), seeds.end(),
                   [&](uint32_t a, uint32_t b) {
                     return hubs_first ? deg[a] > deg[b]
                                       : deg[a] < deg[b];
                   });

  std::vector<uint8_t> visited(nv, 0);
  std::vector<uint32_t> queue;   // FIFO over the whole run: the BFS
  queue.reserve(nv);             // layout IS the output order
  std::vector<uint32_t> nbuf;    // per-vertex neighbor scratch
  size_t head = 0;
  for (uint32_t s : seeds) {
    if (visited[s]) continue;
    visited[s] = 1;
    queue.push_back(s);
    while (head < queue.size()) {
      uint32_t x = queue[head++];
      nbuf.clear();
      for (uint64_t i = off[x]; i < off[x + 1]; i++) {
        uint32_t n = adj[i];
        if (!visited[n]) {
          visited[n] = 1;   // mark at enqueue: adjacency may repeat
          nbuf.push_back(n);
        }
      }
      std::stable_sort(nbuf.begin(), nbuf.end(),
                       [&](uint32_t a, uint32_t b) {
                         return hubs_first ? deg[a] > deg[b]
                                           : deg[a] < deg[b];
                       });
      for (uint32_t n : nbuf) queue.push_back(n);
    }
  }
  if (queue.size() != nv) return -3;  // bijection violated (bug)
  for (uint32_t i = 0; i < nv; i++) perm_out[i] = queue[i];
  return 0;
}
