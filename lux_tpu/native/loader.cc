// liblux_native — partition-slice loader for .lux files.
//
// Native equivalent of the reference's per-partition load tasks
// (reference pull_model.inl:288-319: each CPU task fseeko/freads its
// vertex range's row_ptr and col_idx slices).  Exposed as a C ABI for
// ctypes; multi-threaded chunked pread so multi-GB graph files load at
// disk/page-cache bandwidth instead of through Python.
//
// All functions return 0 on success, negative errno-style codes on
// failure.

#include <fcntl.h>
#include <pthread.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr uint64_t kHeaderSize = 12;  // u32 nv + u64 ne

struct ReadJob {
  int fd;
  uint64_t off;
  uint64_t len;
  char* dst;
  int rc;
};

void* read_worker(void* p) {
  ReadJob* j = static_cast<ReadJob*>(p);
  uint64_t done = 0;
  while (done < j->len) {
    ssize_t r = pread(j->fd, j->dst + done, j->len - done, j->off + done);
    if (r < 0) {
      if (errno == EINTR) continue;
      j->rc = -errno;
      return nullptr;
    }
    if (r == 0) {  // unexpected EOF
      j->rc = -EIO;
      return nullptr;
    }
    done += (uint64_t)r;
  }
  j->rc = 0;
  return nullptr;
}

// Parallel chunked pread of [off, off+len) into dst.
int pread_parallel(int fd, uint64_t off, uint64_t len, void* dst,
                   int threads) {
  if (len == 0) return 0;
  if (threads < 1) threads = 1;
  if (threads > 64) threads = 64;
  uint64_t chunk = (len + threads - 1) / threads;
  std::vector<ReadJob> jobs;
  std::vector<pthread_t> tids;
  for (int t = 0; t < threads; t++) {
    uint64_t o = (uint64_t)t * chunk;
    if (o >= len) break;
    jobs.push_back({fd, off + o, std::min(chunk, len - o),
                    static_cast<char*>(dst) + o, 0});
  }
  tids.resize(jobs.size());
  for (size_t t = 1; t < jobs.size(); t++)
    pthread_create(&tids[t], nullptr, read_worker, &jobs[t]);
  read_worker(&jobs[0]);
  for (size_t t = 1; t < jobs.size(); t++) pthread_join(tids[t], nullptr);
  for (auto& j : jobs)
    if (j.rc) return j.rc;
  return 0;
}

}  // namespace

extern "C" {

// Read nv/ne from the header.
int lux_read_header(const char* path, uint32_t* nv, uint64_t* ne) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -errno;
  char buf[kHeaderSize];
  ssize_t r = pread(fd, buf, kHeaderSize, 0);
  close(fd);
  if (r != (ssize_t)kHeaderSize) return r < 0 ? -errno : -EIO;
  std::memcpy(nv, buf, 4);
  std::memcpy(ne, buf + 4, 8);
  return 0;
}

// Load one partition's slices: vertex range [v0, v1), its row_ptrs
// (END offsets, e_hi - written into row_out[v1-v0]) and its col_idx
// slice [e_lo, e_hi) into col_out.  e_lo/e_hi are returned so the
// caller can size col_out with a first call passing col_out == NULL.
// weight_out, if non-NULL, receives the matching weight slice
// (weight_size = bytes per weight, 4 for i32/f32).
int lux_load_partition(const char* path, uint32_t nv, uint64_t ne,
                       uint32_t v0, uint32_t v1, int weighted,
                       uint32_t weight_size, uint64_t* e_lo,
                       uint64_t* e_hi, uint64_t* row_out,
                       uint32_t* col_out, void* weight_out, int threads) {
  if (v1 > nv || v0 > v1) return -EINVAL;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -errno;

  // Edge range: [row_ptrs[v0-1], row_ptrs[v1-1]).
  uint64_t lo = 0, hi = 0;
  if (v0 > 0) {
    if (pread(fd, &lo, 8, kHeaderSize + 8ull * (v0 - 1)) != 8) {
      close(fd);
      return -EIO;
    }
  }
  if (v1 > 0) {
    if (pread(fd, &hi, 8, kHeaderSize + 8ull * (v1 - 1)) != 8) {
      close(fd);
      return -EIO;
    }
  }
  *e_lo = lo;
  *e_hi = hi;
  if (col_out == nullptr) {  // size query only
    close(fd);
    return 0;
  }

  int rc = 0;
  if (row_out && v1 > v0)
    rc = pread_parallel(fd, kHeaderSize + 8ull * v0, 8ull * (v1 - v0),
                        row_out, threads);
  if (!rc && hi > lo)
    rc = pread_parallel(fd, kHeaderSize + 8ull * nv + 4ull * lo,
                        4ull * (hi - lo), col_out, threads);
  if (!rc && weighted && weight_out && hi > lo)
    rc = pread_parallel(
        fd, kHeaderSize + 8ull * nv + 4ull * ne + (uint64_t)weight_size * lo,
        (uint64_t)weight_size * (hi - lo), weight_out, threads);
  close(fd);
  return rc;
}

// Count out-degrees by streaming col_idx in parallel chunks (the
// reference recomputes degrees at load time the same way, single
// threaded: PullScanTask, pull_model.inl:322-345).
int lux_count_degrees(const char* path, uint32_t nv, uint64_t ne,
                      uint32_t* deg_out, int threads) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -errno;
  std::memset(deg_out, 0, 4ull * nv);
  const uint64_t base = kHeaderSize + 8ull * nv;
  const uint64_t chunk_elems = 1ull << 22;
  std::vector<uint32_t> buf(chunk_elems);
  for (uint64_t e = 0; e < ne; e += chunk_elems) {
    uint64_t n = std::min(chunk_elems, ne - e);
    int rc = pread_parallel(fd, base + 4ull * e, 4ull * n, buf.data(),
                            threads);
    if (rc) {
      close(fd);
      return rc;
    }
    for (uint64_t i = 0; i < n; i++) {
      if (buf[i] >= nv) {
        close(fd);
        return -EINVAL;
      }
      deg_out[buf[i]]++;
    }
  }
  close(fd);
  return 0;
}

}  // extern "C"
