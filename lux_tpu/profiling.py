"""Profiling and tracing.

The reference's observability is wall clocks plus per-part phase
timings printed under -verbose (reference sssp_gpu.cu:513-518,
pagerank.cc:108-118).  The TPU-native equivalents:

- ``trace(dir)``: captures an XLA/TPU profiler trace viewable in
  TensorBoard / Perfetto (the analogue of Legion's prof logs).
- ``PhaseTimer``: host-side phase timing with completion fences
  (load / build / compile / iterate), printed like the reference's
  loadTime/compTime/updateTime breakdown; ``report()`` returns the
  phases list so callers (event logs, tables) consume it directly
  instead of re-parsing stdout.
- ``annotation``/``step_annotation``: host-side
  ``jax.profiler.TraceAnnotation`` wrappers the run paths (timing.py,
  segmented.py, checkpoint.py, engine/phased.py) put around their
  iterate / segment / checkpoint regions, so a captured trace shows
  named regions instead of anonymous XLA ops; the engines' traced
  code additionally carries ``jax.named_scope`` labels (lux_exchange /
  lux_gather / lux_reduce / lux_apply, push: lux_relax / lux_update /
  lux_sparse) that name the device-side ops themselves.
"""

from __future__ import annotations

import contextlib
import time


@contextlib.contextmanager
def trace(log_dir: str | None):
    """Capture a jax.profiler trace into ``log_dir`` (no-op if None)."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
    print(f"profiler trace written to {log_dir}")


def annotation(name: str):
    """Host-side named region for profiler traces
    (jax.profiler.TraceAnnotation); a no-op nullcontext when the
    profiler is unavailable.  Costs nothing outside an active trace
    capture."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:       # noqa: BLE001 — profiling must never break
        return contextlib.nullcontext()


def step_annotation(name: str, step: int):
    """Per-step named region (jax.profiler.StepTraceAnnotation) —
    segments/repeats show up as numbered steps in the trace viewer."""
    try:
        import jax

        return jax.profiler.StepTraceAnnotation(name, step_num=step)
    except Exception:       # noqa: BLE001
        return contextlib.nullcontext()


class _Phase:
    """Set ``.fence`` to a device value produced INSIDE the block to
    include its async execution in the phase time."""

    def __init__(self):
        self.fence = None


class PhaseTimer:
    """Named phase wall-clocks with reliable fences.

    Device work dispatches asynchronously, so a phase that produces
    device values must fence them — assign the result to the phase
    handle (or pass ``fence=`` a zero-arg callable evaluated at exit):

    >>> pt = PhaseTimer()
    >>> with pt.phase("load"):
    ...     g = Graph.from_file(...)
    >>> with pt.phase("iterate") as ph:
    ...     state = eng.run(state, 10)
    ...     ph.fence = state
    >>> pt.report()
    """

    def __init__(self):
        self.phases: list[tuple[str, float]] = []

    @contextlib.contextmanager
    def phase(self, name: str, fence=None):
        h = _Phase()
        with annotation(f"lux_phase_{name}"):
            t0 = time.perf_counter()
            yield h
            f = fence() if callable(fence) else fence
            for val in (f, h.fence):
                if val is not None:
                    from lux_tpu.timing import fetch
                    fetch(val)
            self.phases.append((name, time.perf_counter() - t0))

    def report(self, file=None) -> list[tuple[str, float]]:
        """Print the phase table and RETURN the (name, seconds) phases
        list, so callers (CLI tables, event logs) consume the data
        directly instead of re-parsing stdout."""
        total = sum(t for _, t in self.phases)
        for name, t in self.phases:
            print(f"  {name:<12s} {t:8.3f} s "
                  f"({100 * t / max(total, 1e-12):5.1f}%)", file=file)
        print(f"  {'total':<12s} {total:8.3f} s", file=file)
        return list(self.phases)
