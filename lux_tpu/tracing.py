"""Run tracing & imbalance attribution: span timeline with Perfetto
export, plus the crash flight recorder.

The reference's whole observability story is per-part wall-clock
prints (reference sssp_gpu.cu:513-518, pagerank.cc:108-118); lux_tpu
rounds 7/9/11/12 built a structured event substrate (telemetry.py:
every event carries monotonic ``tm`` + ``pid`` + ``session``), but the
log stayed FLAT — no causality, no cross-process timeline, and a dead
run left no postmortem artifact.  This module is the attribution
layer on top of that substrate, three pillars:

1. **Span model + Perfetto export** (``trace_export``): reconstruct
   the run -> attempt -> segment/timed-run -> phase hierarchy from an
   event stream and emit Chrome-trace/Perfetto JSON
   (``chrome://tracing`` / ui.perfetto.dev loadable).  One trace
   process per (session, pid) stream — heartbeat drills appending
   several OS processes into one file become side-by-side tracks —
   with per-stream wall/monotonic alignment (``tm`` orders within a
   process, the median ``t - tm`` offset aligns across processes).
   Events carrying fenced ``seconds`` (segment, timed_run,
   checkpoint_save) become duration spans ending at their emit time;
   ``phases`` reports unroll into per-iteration phase spans;
   heartbeat/topology/retry/health/budget events become instant
   markers; and an elastic ``mesh_shrink`` moves subsequent execution
   spans onto a NEW track (a visible track transition at the moment
   the mesh changed).  ``validate_trace`` machine-checks the output:
   spans properly nest per track and every non-run span lies inside a
   run span (no orphans).

2. **Per-part counters** live in the engines (round 13 additions to
   the ``*_stats``/``*_health`` loop variants, lux_tpu/engine/*.py)
   and in telemetry.IterStats (``part_totals``/``imbalance``); this
   module's drills exercise them end-to-end and the export carries
   the ``iter_stats`` digest (imbalance index + per-part totals) on
   the run span.

3. **Crash flight recorder** (``FlightRecorder``): a bounded
   in-memory ring of recent events plus the last health word,
   calibration fingerprint and placement metadata, fed by a
   telemetry observer and dumped ATOMICALLY to ``FLIGHT.json`` by the
   resilience supervisor on FATAL failures (HealthError included) and
   topology faults — a run that dies through the tunnel leaves a
   diagnosable artifact.  ``scripts/events_summary.py -flight``
   renders it.

CLI (``python -m lux_tpu.tracing``):

- no arguments: the tier-1 smoke — run the four apps on small CPU
  graphs with telemetry + per-part counters and export ``trace.json``.
- ``FILE...``: export existing ``-events`` JSONL file(s).
- ``-drill``: the 8-virtual-device elastic worker-kill drill — two
  jax.distributed subprocesses (4 CPU devices each) run a
  heartbeat-supervised checkpointed pagerank sharing ONE event file;
  worker 1 is hard-killed mid-run, worker 0 detects the death at the
  heartbeat deadline and agrees on the shrunken topology, and the
  solo relaunch resumes from the shared checkpoint (``replace``
  event).  The merged two-process timeline exports as one trace.  On
  jaxlib CPU builds without multi-process collectives the drill
  falls back to the in-process DEVICE_LOSS elastic drill (same
  recovery machinery, one process).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import sys
import tempfile
import time
from statistics import median

from lux_tpu import telemetry

SCHEMA = 1

# event kinds whose fenced ``seconds`` is a real duration ending at
# the emit time -> duration spans on the execution track
SPAN_KINDS = {"segment", "timed_run", "checkpoint_save"}
# run boundaries (one CLI invocation / bench config each)
RUN_BOUNDARIES = ("run_start", "config_start")
# instant markers promoted to PROCESS scope (big visual arrows)
PROCESS_INSTANTS = {"mesh_shrink", "topology_fault", "replace",
                    "failure", "health_trip", "flight_dump"}
# timed_phases report keys that are counters, not phase seconds
META_KEYS = ("frontier", "bucket", "advances")
# round 19 (lux_tpu/comms.py): phases whose span subdivides into
# per-collective child spans when the run carries a comm_ledger event
# with a priced wire time (the engines' COMM_PHASES anchor)
COMM_PHASE_NAMES = ("exchange", "gen_exchange")

# per-query serving spans (round 17): query tracks start here, one
# LANE per set of non-overlapping queries (greedy interval packing —
# an oversubscribed load renders as stacked lanes whose depth IS the
# concurrency), leaving tid 1..99 to the execution epochs.  Round 18
# (serving fleet, lux_tpu/fleet.py): lanes group PER REPLICA — each
# replica group gets a contiguous tid range starting at
# QUERY_TID_BASE, sized max(QUERY_REPLICA_STRIDE, its lane count)
# (so small traces keep stable base+group*stride tids and a deep
# group can never collide into the next group's range), and a
# failover renders as the qid's span SPLITTING onto the new
# replica's track group (the round-13 mesh-shrink epoch pattern
# applied to query lanes; ``validate_trace`` machine-checks the
# transition).
QUERY_TID_BASE = 100
QUERY_REPLICA_STRIDE = 40


def _num(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool) \
        and x == x and abs(x) != float("inf")


# ---------------------------------------------------------------------
# event loading / stream splitting (wire format of telemetry.EventLog)

def load_events(path: str):
    """Tolerant JSONL load -> (events, errors)."""
    events, errs = [], []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError as e:
                errs.append(f"{path}:{i}: unparseable JSON ({e})")
                continue
            if not isinstance(ev, dict) or "kind" not in ev:
                errs.append(f"{path}:{i}: event without a 'kind'")
                continue
            events.append(ev)
    if not events and not errs:
        errs.append(f"{path}: no events found")
    return events, errs


def split_streams(events):
    """[(key, events)] per (session, pid) stream in first-appearance
    order — the round-12 merge key; legacy events (no session/pid)
    share the ``None`` stream."""
    streams, order = {}, []
    for ev in events:
        key = None
        if "session" in ev or "pid" in ev:
            key = (ev.get("session"), ev.get("pid"))
        if key not in streams:
            streams[key] = []
            order.append(key)
        streams[key].append(ev)
    return [(k, streams[k]) for k in order]


def split_runs(events):
    """Group one stream into runs at run_start/config_start
    boundaries; a log without boundary events is one anonymous run."""
    runs, cur = [], []
    for ev in events:
        if ev["kind"] in RUN_BOUNDARIES and cur:
            runs.append(cur)
            cur = []
        cur.append(ev)
    if cur:
        runs.append(cur)
    return runs


# ---------------------------------------------------------------------
# pillar 1: span reconstruction + Chrome-trace/Perfetto export

@dataclasses.dataclass
class _Track:
    """Mutable per-stream export state (epoch = mesh-shrink count:
    execution spans after a shrink move to a new tid — the track
    transition that makes an elastic drill readable)."""
    pid: int
    epoch: int = 0
    shrink_labels: dict = dataclasses.field(default_factory=dict)


def _stream_offset(evs) -> float:
    """Median wall-minus-monotonic offset: aligns this process's
    monotonic timestamps onto the (roughly) shared wall clock."""
    ds = [ev["t"] - ev["tm"] for ev in evs
          if _num(ev.get("t")) and _num(ev.get("tm"))]
    return median(ds) if ds else 0.0


def _ats(ev, off) -> float | None:
    """Aligned absolute seconds of one event (monotonic + offset;
    wall-clock fallback for legacy events)."""
    if _num(ev.get("tm")):
        return ev["tm"] + off
    if _num(ev.get("t")):
        return ev["t"]
    return None


def _span(name, cat, ts, dur, pid, tid, args=None) -> dict:
    out = {"name": str(name), "cat": cat, "ph": "X",
           "ts": round(ts, 1), "dur": round(max(dur, 0.0), 1),
           "pid": pid, "tid": tid}
    if args:
        out["args"] = args
    return out


def _instant(name, ts, pid, tid, scope="t", args=None) -> dict:
    out = {"name": str(name), "cat": "marker", "ph": "i",
           "ts": round(ts, 1), "pid": pid, "tid": tid, "s": scope}
    if args:
        out["args"] = args
    return out


def _meta(name, pid, value, tid=None) -> dict:
    out = {"name": name, "ph": "M", "pid": pid,
           "args": {"name" if name.endswith("_name")
                    else "sort_index": value}}
    if tid is not None:
        out["tid"] = tid
    return out


def _clamp(ts, dur, lo, hi):
    ts = min(max(ts, lo), hi)
    return ts, max(0.0, min(dur, hi - ts))


def _span_name(ev) -> str:
    k = ev["kind"]
    if k == "segment":
        n = ev.get("n", ev.get("iters"))
        return f"segment[{ev.get('engine', '?')} n={n}]"
    if k == "timed_run":
        return f"timed_run[{ev.get('repeat', 0)}]"
    return k


def _run_spans(run, us, trk: _Track, te: list):
    """Emit one run's spans into ``te``: the run span + attempt spans
    on tid 0, execution/phase spans on tid 1+epoch, everything else
    as instant markers.  Child spans are clamped into the run extent
    so the nesting invariant holds by construction."""
    times = [us(ev) for ev in run]
    rstart, rend = min(times), max(times)
    head = run[0] if run[0]["kind"] in RUN_BOUNDARIES else {}
    name = head.get("app") or head.get("config") or "run"
    args = {k: head[k] for k in ("app", "config", "file", "mesh",
                                 "drill", "worker") if k in head}
    # the counters digest (imbalance + per-part totals) rides the run
    # span so Perfetto's selection panel shows the attribution
    for ev in run:
        if ev["kind"] == "iter_stats":
            args["iter_stats"] = {
                k: v for k, v in ev.items()
                if k in ("engine", "iters", "imbalance", "parts",
                         "parts_edges", "parts_changed", "edges_sum",
                         "changed_sum")}
    te.append(_span(name, "run", rstart, rend - rstart, trk.pid, 0,
                    args=args or None))
    # round 19: the run's comm ledgers, keyed by app (a decompose run
    # holds several apps in one stream) — each phases event below
    # subdivides with ITS app's ledger; a lone ledger also serves
    # phases events that carry no app tag (the CLI -phases shape)
    comm_by_app = {}
    for ev in run:
        if ev["kind"] == "comm_ledger":
            comm_by_app[ev.get("app", ev.get("config"))] = ev

    # attempt spans: boundaries at retry / handled-topology events
    # (supervise() retries immediately after a handled topology fault
    # and after the retry backoff otherwise)
    bounds = [rstart]
    for ev, ts in zip(run, times):
        if ev["kind"] == "retry" or (ev["kind"] == "topology_fault"
                                     and ev.get("handled")):
            bounds.append(ts)
    bounds.append(rend)
    for i in range(len(bounds) - 1):
        a, b = bounds[i], bounds[i + 1]
        te.append(_span(f"attempt {i}", "attempt", a, b - a,
                        trk.pid, 0))

    for ev, ts in zip(run, times):
        kind = ev["kind"]
        tid = 1 + trk.epoch
        if kind in SPAN_KINDS and _num(ev.get("seconds")):
            dur = ev["seconds"] * 1e6
            s, d = _clamp(ts - dur, dur, rstart, rend)
            te.append(_span(_span_name(ev), "exec", s, d, trk.pid,
                            tid, args={k: v for k, v in ev.items()
                                       if k in ("n", "done", "iters",
                                                "total", "active",
                                                "repeat", "iter",
                                                "path", "engine")}))
        elif kind == "phases":
            report = [r for r in ev.get("report", [])
                      if isinstance(r, dict)]
            total = sum(v for r in report for k, v in r.items()
                        if k not in META_KEYS and _num(v)) * 1e6
            cur = max(rstart, ts - total)
            comm = comm_by_app.get(ev.get("app"))
            if comm is None and "app" not in ev \
                    and len(comm_by_app) == 1:
                comm = next(iter(comm_by_app.values()))
            for i, r in enumerate(report):
                for ph, v in r.items():
                    if ph in META_KEYS or not _num(v):
                        continue
                    d = v * 1e6
                    s, d = _clamp(cur, d, rstart, rend)
                    te.append(_span(f"i{i}:{ph}", "phase", s, d,
                                    trk.pid, tid))
                    if ph in COMM_PHASE_NAMES:
                        te.extend(_collective_spans(
                            comm, i, ph, s, d, trk.pid, tid))
                    cur += d
        elif kind == "mem_sample":
            # round-22 memory observatory: the occupancy trail draws
            # as a Chrome COUNTER track ("C" phase) — live bytes +
            # the peak watermark as stacked series, one counter row
            # per replica when the sample is labeled
            cname = "memory"
            if ev.get("replica"):
                cname = f"memory:{ev['replica']}"
            te.append({"name": cname, "ph": "C", "ts": ts,
                       "pid": trk.pid,
                       "args": {"live_bytes":
                                int(ev.get("live_bytes", 0)),
                                "peak_bytes":
                                int(ev.get("peak_bytes", 0))}})
        elif kind in RUN_BOUNDARIES:
            pass                       # represented by the run span
        else:
            scope = "p" if kind in PROCESS_INSTANTS else "t"
            iargs = {k: v for k, v in ev.items()
                     if k not in ("t", "tm", "pid", "session", "kind")
                     and isinstance(v, (int, float, str, bool))}
            te.append(_instant(kind, ts, trk.pid, tid, scope=scope,
                               args=iargs or None))
        if kind == "mesh_shrink":
            trk.epoch += 1
            to = ev.get("to_ndev", ev.get("to_nproc"))
            trk.shrink_labels[trk.epoch] = (
                f"exec (after shrink #{trk.epoch}"
                + (f", ndev={to}" if _num(to) else "") + ")")
    _query_spans(run, times, trk, te, rstart, rend)


def _collective_spans(comm, i, ph, s, d, pid, tid) -> list:
    """Per-collective child spans inside one exchange-phase span
    (round 19, lux_tpu/comms.py): the ledger's priced wire window —
    min(predicted wire seconds, the measured phase) — sits at the
    START of the phase (the collective launches before the epilogue
    consumes it), subdivided proportionally to each collective's
    shipped bytes.  Emitted only when the ledger carries a priced
    wire time (a measured link rate existed): an unpriced guess must
    not render as measurement.  Children lie strictly inside the
    phase span, so the nesting validator holds by construction."""
    if not isinstance(comm, dict) or d <= 0:
        return []
    pred = comm.get("predicted_s")
    groups = comm.get("per_collective")
    if not _num(pred) or pred <= 0 or not isinstance(groups, list):
        return []
    ents = [g for g in groups if isinstance(g, dict)
            and _num(g.get("shipped_bytes")) and g["shipped_bytes"] > 0]
    # cond branches are ALTERNATIVES: predicted_s prices the steady
    # path (unconditional + heaviest branch, the ledger convention),
    # so the subdivision keeps exactly that path — rendering a branch
    # that did not run would show collectives the iteration never
    # launched
    by_branch: dict = {}
    for g in ents:
        by_branch.setdefault(g.get("branch") or "", []).append(g)
    keep = by_branch.pop("", [])
    if by_branch:
        keep += max(by_branch.values(),
                    key=lambda gs: sum(g["shipped_bytes"]
                                       for g in gs))
    ents = keep
    total = sum(g["shipped_bytes"] for g in ents)
    if total <= 0:
        return []
    win = min(pred * 1e6, d)
    out, cur = [], s
    for g in ents:
        cd = win * g["shipped_bytes"] / total
        cur2, cd = _clamp(cur, cd, s, s + d)
        out.append(_span(f"i{i}:{ph}:{g.get('prim')}", "collective",
                         cur2, cd, pid, tid,
                         args={"shipped_bytes": g["shipped_bytes"],
                               "count": g.get("count"),
                               "tier": g.get("tier")}))
        cur = cur2 + cd
    return out


def _merge_windows(windows):
    """Sorted, overlap-merged [(s, e)] — sibling spans on one track
    must be disjoint for the nesting validator."""
    out = []
    for s, e in sorted(windows):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _query_spans(run, times, trk: _Track, te: list, rstart, rend):
    """Per-query serving spans (round 17, lux_tpu/serve.py events):
    each retired query becomes a ``query`` span from its enqueue to
    its retirement, with ``query_phase`` children splitting the life
    into the queue WAIT (enqueue -> column assignment) and the
    engine segments that carried it — so a query's wait-vs-compute
    renders visibly in Perfetto.  Queries pack greedily onto
    ``queries.N`` lanes (one lane per set of non-overlapping
    queries); everything is clamped into the run extent so the
    run-nesting invariant holds by construction, and
    ``validate_trace`` machine-checks the query/query_phase nesting
    rule.

    Round 18 (serving fleet): lanes group PER REPLICA (thread name
    ``queries[replica].N``), and a ``failover`` event SPLITS the
    qid's span at the failover instant — the pre-failover segment
    stays on the dead replica's track group, the post-failover
    segment (named ``... (failover)``, args carrying
    ``failover_from``/``failover_to``) moves onto the survivor's —
    the same track-transition idiom the mesh-shrink epochs use, now
    on the query lanes."""
    enq, starts, done, fo = {}, {}, {}, {}
    segs = []
    for ev, ts in zip(run, times):
        kind = ev["kind"]
        qid = ev.get("qid")
        if kind == "query_enqueue":
            enq.setdefault(qid, ts)
        elif kind == "query_start":
            starts.setdefault(qid, []).append((ts, ev))
        elif kind == "failover":
            fo.setdefault(qid, []).append((ts, ev))
        elif kind == "query_done":
            done[qid] = (ts, ev)
        elif kind == "segment" and _num(ev.get("seconds")):
            d = ev["seconds"] * 1e6
            segs.append((ts - d, ts))
    if not done:
        return
    segs = _merge_windows(segs)
    qs = []
    for qid, (tend, ev) in done.items():
        t0 = enq.get(qid)
        if t0 is None and _num(ev.get("latency_s")):
            t0 = tend - ev["latency_s"] * 1e6
        sl = starts.get(qid) or []
        t1 = sl[0][0] if sl else None
        if t1 is None and t0 is not None and _num(ev.get("wait_s")):
            t1 = t0 + ev["wait_s"] * 1e6
        t0 = tend if t0 is None else t0
        t1 = t0 if t1 is None else t1
        t0 = min(max(t0, rstart), rend)          # clamp + order
        t1 = min(max(t1, t0), rend)
        tend = min(max(tend, t1), rend)
        qs.append((t0, t1, tend, qid, ev))
    qs.sort(key=lambda x: (x[0], x[2]))
    groups: dict = {}           # replica -> lane-group index
    lane_ends: dict = {}        # group -> per-lane end times
    lane_labels: dict = {}      # (group, lane) -> thread label
    placed: list = []           # (group, lane, span dict) pending tid

    def lane_of(replica, s, e):
        group = groups.setdefault(replica, len(groups))
        ends = lane_ends.setdefault(group, [])
        lane = next((i for i, x in enumerate(ends) if x <= s), None)
        if lane is None:
            lane = len(ends)
            ends.append(e)
            lane_labels[(group, lane)] = (
                f"queries.{lane}" if replica is None
                else f"queries[{replica}].{lane}")
        else:
            ends[lane] = max(ends[lane], e)
        return group, lane

    for t0, t1, tend, qid, ev in qs:
        sl = starts.get(qid) or []
        fos = sorted(fo.get(qid) or [], key=lambda x: x[0])
        cuts = [t0]
        for ts_f, _fev in fos:
            cuts.append(min(max(ts_f, cuts[-1]), tend))
        cuts.append(tend)

        def replica_of(i):
            if i == 0:
                # the failover record is authoritative for the
                # pre-failover replica: a query killed while still
                # QUEUED on the dead replica has its first
                # query_start on the survivor, but its first life
                # segment belongs to the replica it was assigned to
                if fos:
                    return fos[0][1].get("from_replica")
                if sl:
                    return sl[0][1].get("replica")
                return ev.get("replica")
            return fos[i - 1][1].get("to_replica")

        base = {k: v for k, v in ev.items()
                if k in ("qid", "query_kind", "col", "iters",
                         "segments", "latency_s", "wait_s",
                         "converged", "slo_ms", "slo_ok")}
        for i in range(len(cuts) - 1):
            s, e = cuts[i], cuts[i + 1]
            replica = replica_of(i)
            gl = lane_of(replica, s, e)
            args = dict(base)
            if replica is not None:
                args["replica"] = replica
            name = f"q{qid} [{ev.get('query_kind', '?')}]"
            if i > 0:
                fev = fos[i - 1][1]
                args["failover_from"] = fev.get("from_replica")
                args["failover_to"] = fev.get("to_replica")
                name += " (failover)"
            placed.append((*gl, _span(name, "query", s, e - s,
                                      trk.pid, 0, args=args)))
            lo = min(max(t1, s), e) if i == 0 else s
            if i == 0 and lo > s:
                placed.append((*gl, _span("wait", "query_phase", s,
                                          lo - s, trk.pid, 0)))
            resident = False
            for s0, s1 in segs:
                a, b = max(s0, lo), min(s1, e)
                if b > a:
                    placed.append((*gl, _span("seg", "query_phase",
                                              a, b - a, trk.pid, 0)))
                    resident = True
            if not resident and e > lo:
                # no overlapping segment events (sparse log): one
                # undifferentiated residency child keeps
                # wait-vs-compute readable
                placed.append((*gl, _span("resident", "query_phase",
                                          lo, e - lo, trk.pid, 0)))

    # tid assignment is a SECOND pass: each replica group gets a
    # contiguous lane range sized by its ACTUAL lane count (at least
    # QUERY_REPLICA_STRIDE, so small traces keep the stable
    # base+group*stride tids) — a group needing more lanes than the
    # stride can never collide into the next group's track range
    offsets, off = {}, 0
    for group in sorted(lane_ends):
        offsets[group] = off
        off += max(QUERY_REPLICA_STRIDE, len(lane_ends[group]))
    for (group, lane), label in sorted(lane_labels.items()):
        te.append(_meta("thread_name", trk.pid, label,
                        tid=QUERY_TID_BASE + offsets[group] + lane))
    for group, lane, span in placed:
        span["tid"] = QUERY_TID_BASE + offsets[group] + lane
        te.append(span)


def trace_export(events, out: str | None = None) -> dict:
    """Chrome-trace/Perfetto JSON for a (possibly multi-process)
    telemetry event list.  One trace process per (session, pid)
    stream; ``out`` additionally writes the JSON atomically.  Returns
    the trace dict ({"traceEvents": [...], ...})."""
    streams = split_streams(events)
    offs = {key: _stream_offset(evs) for key, evs in streams}
    t0s = [t for key, evs in streams
           for t in (_ats(ev, offs[key]) for ev in evs)
           if t is not None]
    t0 = min(t0s) if t0s else 0.0
    te: list = []
    for si, (key, evs) in enumerate(streams):
        trk = _Track(pid=si)
        off = offs[key]

        def us(ev, _off=off):
            a = _ats(ev, _off)
            return 0.0 if a is None else (a - t0) * 1e6

        session, ospid = key if key is not None else (None, None)
        pname = (f"session {session} pid {ospid}"
                 if key is not None else "events")
        te.append(_meta("process_name", si, pname))
        te.append(_meta("process_sort_index", si, si))
        te.append(_meta("thread_name", si, "run/attempt", tid=0))
        for run in split_runs(evs):
            _run_spans(run, us, trk, te)
        te.append(_meta("thread_name", si, "exec", tid=1))
        for epoch, label in trk.shrink_labels.items():
            te.append(_meta("thread_name", si, label, tid=1 + epoch))
    trace = {"traceEvents": te, "displayTimeUnit": "ms",
             "otherData": {"schema": SCHEMA,
                           "generator": "lux_tpu.tracing",
                           "streams": len(streams)}}
    if out:
        _atomic_write_json(out, trace)
    return trace


def _atomic_write_json(path: str, doc) -> None:
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


# span endpoints inherit the 1e-6 s rounding of ``tm``/``seconds``:
# tolerate up to 2 us of slack before calling two spans overlapping
_EPS_US = 2.0


def validate_trace(trace, eps_us: float = _EPS_US) -> list[str]:
    """Machine-check a trace: known phases only, numeric
    ts/dur, PROPER NESTING per (pid, tid) track (two spans either
    disjoint or one contains the other), no orphan spans (every
    non-run span lies inside some run span of its process), and —
    round 17 — the per-query nesting rule: every ``query`` span
    carries its qid, and every ``query_phase`` span (wait / seg /
    resident) lies inside some ``query`` span of its own track.
    Returns error strings; empty = valid."""
    errs: list[str] = []
    evs = trace.get("traceEvents") if isinstance(trace, dict) else None
    if not isinstance(evs, list) or not evs:
        return ["traceEvents missing or empty"]
    spans: dict = {}
    runs: dict = {}
    qspans: dict = {}
    qphases: dict = {}
    qrecords: list = []
    for i, e in enumerate(evs):
        if not isinstance(e, dict):
            errs.append(f"traceEvents[{i}]: not an object")
            continue
        ph = e.get("ph")
        if ph not in ("X", "i", "I", "M"):
            errs.append(f"traceEvents[{i}]: unknown ph {ph!r}")
            continue
        if ph == "M":
            continue
        if not isinstance(e.get("name"), str):
            errs.append(f"traceEvents[{i}]: non-string name")
        if not _num(e.get("ts")):
            errs.append(f"traceEvents[{i}]: non-numeric ts")
            continue
        if ph == "X":
            if not _num(e.get("dur")) or e["dur"] < 0:
                errs.append(f"traceEvents[{i}] {e.get('name')!r}: "
                            f"bad dur {e.get('dur')!r}")
                continue
            spans.setdefault((e.get("pid"), e.get("tid")),
                             []).append(e)
            if e.get("cat") == "run":
                runs.setdefault(e.get("pid"), []).append(
                    (e["ts"], e["ts"] + e["dur"]))
            elif e.get("cat") == "query":
                if not isinstance((e.get("args") or {}).get("qid"),
                                  int):
                    errs.append(f"traceEvents[{i}] {e.get('name')!r}:"
                                f" query span without an integer "
                                f"args.qid")
                qspans.setdefault((e.get("pid"), e.get("tid")),
                                  []).append(
                    (e["ts"], e["ts"] + e["dur"]))
                qrecords.append(e)
            elif e.get("cat") == "query_phase":
                qphases.setdefault((e.get("pid"), e.get("tid")),
                                   []).append(e)
    for (pid, tid), sp in spans.items():
        sp.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: list[float] = []
        for e in sp:
            s, end = e["ts"], e["ts"] + e["dur"]
            while stack and stack[-1] <= s + eps_us:
                stack.pop()
            if stack and end > stack[-1] + eps_us:
                errs.append(
                    f"track (pid {pid}, tid {tid}): span "
                    f"{e['name']!r} [{s}, {end}] overlaps the "
                    f"enclosing span ending at {stack[-1]} — spans "
                    f"must nest")
            stack.append(end)
    for (pid, _tid), sp in spans.items():
        rl = runs.get(pid)
        if not rl:
            continue            # hand-made trace without run spans
        for e in sp:
            if e.get("cat") == "run":
                continue
            s, end = e["ts"], e["ts"] + e["dur"]
            if not any(rs - eps_us <= s and end <= re + eps_us
                       for rs, re in rl):
                errs.append(f"orphan span {e['name']!r} (pid {pid}): "
                            f"[{s}, {end}] lies in no run span")
    # round 17: a query phase (wait/seg/resident) outside every query
    # span of its track is an orphan — the wait-vs-compute split
    # would be attributed to no query
    for key, phases in qphases.items():
        ql = qspans.get(key, [])
        for e in phases:
            s, end = e["ts"], e["ts"] + e["dur"]
            if not any(qs - eps_us <= s and end <= qe + eps_us
                       for qs, qe in ql):
                errs.append(
                    f"track (pid {key[0]}, tid {key[1]}): "
                    f"query_phase span {e['name']!r} [{s}, {end}] "
                    f"lies in no query span — per-query phases must "
                    f"nest inside their query")
    # round 18 (serving fleet): a qid appearing as MULTIPLE query
    # spans is a failover split — every span after the first must
    # carry its failover record and sit on a DIFFERENT track (the
    # new replica's lane group); anything else is either a duplicate
    # retirement or a failover that did not transition tracks.
    # Scoped to the containing run window so qids legitimately reused
    # across runs in one stream don't conflate.
    by_qid: dict = {}
    for e in qrecords:
        qid = (e.get("args") or {}).get("qid")
        if not isinstance(qid, int):
            continue            # reported above
        pid = e.get("pid")
        rl = runs.get(pid) or []
        w = next((i for i, (rs, re) in enumerate(rl)
                  if rs - eps_us <= e["ts"]
                  and e["ts"] + e["dur"] <= re + eps_us), None)
        by_qid.setdefault((pid, w, qid), []).append(e)
    for (pid, _w, qid), lst in by_qid.items():
        if len(lst) < 2:
            continue
        lst.sort(key=lambda e: e["ts"])
        for prev, cur in zip(lst, lst[1:]):
            args = cur.get("args") or {}
            if "failover_from" not in args:
                errs.append(
                    f"qid {qid} (pid {pid}): {len(lst)} query spans "
                    f"but the span at ts {cur['ts']} carries no "
                    f"failover record — a qid must retire exactly "
                    f"once")
                continue
            if cur.get("tid") == prev.get("tid"):
                errs.append(
                    f"qid {qid} (pid {pid}): post-failover segment "
                    f"at ts {cur['ts']} sits on the SAME track (tid "
                    f"{cur.get('tid')}) as the segment it continues "
                    f"— a failover must transition onto the new "
                    f"replica's track")
            rep = args.get("replica")
            if rep is not None and args.get("failover_to") is not None \
                    and rep != args["failover_to"]:
                errs.append(
                    f"qid {qid} (pid {pid}): post-failover segment "
                    f"claims replica {rep!r} but its failover record "
                    f"names {args['failover_to']!r} — the span "
                    f"contradicts its own transition")
    return errs


# ---------------------------------------------------------------------
# pillar 3: crash flight recorder

FLIGHT_DEFAULT = "FLIGHT.json"
FLIGHT_CAPACITY = 256


class FlightRecorder:
    """Bounded postmortem ring: the last ``capacity`` telemetry
    events plus the most recent health word, calibration fingerprint
    and placement metadata, dumped atomically on demand.  Installed
    as a telemetry observer (``install_flight_recorder``); the
    resilience supervisor dumps it on FATAL failures and topology
    faults, so a run that dies through the tunnel leaves
    ``FLIGHT.json`` behind."""

    def __init__(self, path: str = FLIGHT_DEFAULT,
                 capacity: int = FLIGHT_CAPACITY):
        self.path = path
        self.ring: collections.deque = collections.deque(
            maxlen=max(1, int(capacity)))
        self.last_health: dict | None = None
        self.last_calibration: dict | None = None
        self.placement: dict = {}
        self.dumps = 0
        # round-22 memory observatory: the occupancy trail survives
        # the main ring's churn — a fatal's postmortem always shows
        # the memory history even when chatty per-query events have
        # already rotated the samples out of the ring
        self.mem_trail: collections.deque = collections.deque(
            maxlen=64)

    def record(self, ev: dict) -> None:
        self.ring.append(ev)
        k = ev.get("kind")
        if k in ("health", "health_trip"):
            self.last_health = ev
        elif k == "calibration":
            self.last_calibration = ev
            if _num(ev.get("ndev")):
                self.placement["ndev"] = ev["ndev"]
        elif k == "header":
            for f in ("nv", "ne", "num_parts"):
                if f in ev:
                    self.placement[f] = ev[f]
        elif k == "mesh_shrink":
            to = ev.get("to_ndev", ev.get("to_nproc"))
            if _num(to):
                self.placement["ndev"] = to
            self.placement["shrinks"] = \
                self.placement.get("shrinks", 0) + 1
        elif k == "replace":
            if _num(ev.get("to_ndev")):
                self.placement["ndev"] = ev["to_ndev"]
        elif k in ("mem_sample", "mem_watermark", "mem_pressure"):
            self.mem_trail.append(ev)

    def snapshot(self, reason=None, classification=None) -> dict:
        counts: dict = {}
        for ev in self.ring:
            counts[ev.get("kind")] = counts.get(ev.get("kind"), 0) + 1
        return {"schema": SCHEMA, "t": round(time.time(), 6),
                "session": telemetry.session_id(), "pid": os.getpid(),
                "reason": reason, "classification": classification,
                "placement": self.placement or None,
                "health": self.last_health,
                "calibration": self.last_calibration,
                "counts": counts,
                "mem_trail": list(self.mem_trail) or None,
                "events": list(self.ring)}

    def dump(self, reason=None, classification=None) -> str:
        """Atomic write (tmp + rename: a crash mid-dump can never
        leave a torn FLIGHT.json) -> the dump path."""
        doc = self.snapshot(reason, classification)
        _atomic_write_json(self.path, doc)
        self.dumps += 1
        telemetry.current().emit(
            "flight_dump", path=self.path,
            reason=None if reason is None else str(reason)[:300],
            classification=classification, events=len(doc["events"]))
        return self.path


_RECORDER: FlightRecorder | None = None


def install_flight_recorder(path: str = FLIGHT_DEFAULT,
                            capacity: int = FLIGHT_CAPACITY
                            ) -> FlightRecorder:
    """Install (or replace) the process flight recorder as a
    telemetry observer.  Idempotent per path; the CLI's ``-flight``
    and bench.py's ``-flight`` call this."""
    global _RECORDER
    uninstall_flight_recorder()
    _RECORDER = FlightRecorder(path, capacity)
    telemetry.add_observer(_RECORDER.record)
    return _RECORDER


def uninstall_flight_recorder() -> None:
    global _RECORDER
    if _RECORDER is not None:
        telemetry.remove_observer(_RECORDER.record)
        _RECORDER = None


def flight_recorder() -> FlightRecorder | None:
    return _RECORDER


def flight_dump(reason=None, classification=None) -> str | None:
    """Dump the installed recorder (no-op None when none is
    installed) — the resilience supervisor's crash hook."""
    if _RECORDER is None:
        return None
    return _RECORDER.dump(reason, classification)


def load_flight(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "events" not in doc:
        raise ValueError(f"{path}: not a flight-recorder dump "
                         f"(no 'events')")
    return doc


# ---------------------------------------------------------------------
# drills + CLI

SMOKE_APPS = ("pagerank", "cc", "sssp", "colfilter")


def _emit_iter_stats(tel, st) -> None:
    if st.kind is None:
        return
    tel.emit("iter_stats", **{("engine" if k == "kind" else k): v
                              for k, v in st.summary().items()})


def run_smoke(events_path: str, apps=SMOKE_APPS, scale: int = 8,
              ef: int = 8, np_parts: int = 2) -> None:
    """The tier-1 smoke: run each app once on a small CPU graph with
    telemetry + per-part counters, leaving an events JSONL the
    exporter (and events_summary) consume."""
    from lux_tpu.observe import _build_app_engine
    from lux_tpu.timing import timed_converge, timed_fused_run

    ev = telemetry.EventLog(events_path)
    st = telemetry.IterStats()
    with telemetry.use(events=ev, iter_stats=st) as tel:
        for app in apps:
            eng = _build_app_engine(app, scale, ef, np_parts, None)
            tel.emit("run_start", schema=telemetry.SCHEMA, app=app)
            tel.emit("header", schema=telemetry.SCHEMA,
                     **eng.sg.telemetry_header())
            if hasattr(eng, "converge"):           # push engines
                _labels, iters, elapsed = timed_converge(eng,
                                                         repeats=1)
            else:
                _state, elapsed = timed_fused_run(eng, 5, repeats=1)
                iters = 5
            tel.emit("run_done", seconds=round(elapsed[0], 6),
                     iters=iters)
            _emit_iter_stats(tel, st)
    ev.close()


def run_loss_drill(workdir: str, events_path: str, ni: int = 12,
                   segment: int = 3) -> None:
    """In-process elastic drill: an 8-virtual-device supervised
    pagerank run hit by an injected DEVICE_LOSS at segment boundary 1
    re-places onto the surviving half-mesh and finishes — the event
    trail carries topology_fault/mesh_shrink/replace."""
    import jax

    from lux_tpu import faults, resilience
    from lux_tpu.apps import pagerank
    from lux_tpu.convert import uniform_random_edges
    from lux_tpu.graph import Graph
    from lux_tpu.parallel.mesh import make_mesh

    ndev = len(jax.devices())
    nd = max(n for n in (2, 4, 8) if n <= ndev) if ndev >= 2 else 0
    if not nd:
        raise RuntimeError(
            "the elastic drill needs >= 2 devices (run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    src, dst = uniform_random_edges(256, 2000, seed=7)
    g = Graph.from_edges(src, dst, 256)

    def factory(mesh):
        return pagerank.build_engine(g, num_parts=nd, mesh=mesh)

    eng = factory(make_mesh(nd))
    plan = faults.FaultPlan(schedule={1: faults.DEVICE_LOSS},
                            lose=nd // 2)
    path = os.path.join(workdir, "drill.ckpt.npz")
    ev = telemetry.EventLog(events_path)
    st = telemetry.IterStats()
    with telemetry.use(events=ev, iter_stats=st) as tel:
        tel.emit("run_start", schema=telemetry.SCHEMA, app="pagerank",
                 drill="device_loss", mesh=nd)
        tel.emit("header", schema=telemetry.SCHEMA,
                 **eng.sg.telemetry_header())
        t0 = time.perf_counter()
        _state, report = resilience.supervised_run(
            eng, ni, path, segment=segment, faults=plan,
            elastic=factory,
            policy=resilience.RetryPolicy(retries=2, jitter=0,
                                          sleep=lambda s: None))
        tel.emit("run_done",
                 seconds=round(time.perf_counter() - t0, 6), iters=ni)
        _emit_iter_stats(tel, st)
        if not report.topology:
            raise RuntimeError("drill fault never fired")
    ev.close()


# -- the 2-subprocess worker-kill drill (tests/test_worker_kill.py's
#    shape, with one SHARED event file exercising the line-atomic
#    multi-writer appends) ----------------------------------------------

_DRILL_NI, _DRILL_SEG, _DRILL_PARTS = 10, 3, 8

import re as _re

_CPU_MP_UNSUPPORTED = _re.compile(
    r"[Mm]ultiprocess computations aren'?t implemented on the CPU "
    r"backend")


def _drill_graph():
    from lux_tpu.convert import uniform_random_edges
    from lux_tpu.graph import Graph

    src, dst = uniform_random_edges(128, 900, seed=5)
    return Graph.from_edges(src, dst, 128)


def _drill_worker_distributed(pid: int, nproc: int, port: str,
                              workdir: str) -> int:
    from lux_tpu import faults, heartbeat, resilience
    from lux_tpu.apps import pagerank
    from lux_tpu.parallel import multihost

    multihost.initialize(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nproc, process_id=pid)
    g = _drill_graph()
    mesh = multihost.global_mesh()
    eng = pagerank.build_engine(g, num_parts=_DRILL_PARTS, mesh=mesh)
    hb = heartbeat.Heartbeat(path=os.path.join(workdir, "hb"),
                             pid=pid, nproc=nproc, deadline_s=20.0)
    plan = None
    if pid == 1:
        plan = faults.FaultPlan(schedule={1: faults.WORKER_KILL},
                                hard_kill=True)
    ckpt = os.path.join(workdir, "drill.ckpt.npz")
    ev = telemetry.EventLog(os.path.join(workdir, "events.jsonl"))
    with telemetry.use(events=ev) as tel:
        tel.emit("run_start", schema=telemetry.SCHEMA, app="pagerank",
                 drill="worker_kill", worker=pid)
        t0 = time.perf_counter()
        try:
            # guard=False: the finite guard fetches the global state
            # at every boundary; the heartbeat IS the boundary check
            resilience.supervised_run(
                eng, _DRILL_NI, ckpt, segment=_DRILL_SEG, faults=plan,
                heartbeat=hb, guard=False,
                policy=resilience.RetryPolicy(retries=0, jitter=0,
                                              sleep=lambda s: None))
        except heartbeat.WorkerLostError:
            survivors = hb.survivors()
            hb.propose_shrink(survivors, generation=1)
            print(f"DRILL_SHRINK pid={pid} survivors={survivors}",
                  flush=True)
            ev.close()
            return 3                  # degraded relaunch requested
        tel.emit("run_done",
                 seconds=round(time.perf_counter() - t0, 6),
                 iters=_DRILL_NI)
    ev.close()
    print(f"DRILL_OK pid={pid}", flush=True)
    return 0


def _drill_worker_solo(workdir: str) -> int:
    import jax

    from lux_tpu import resilience
    from lux_tpu.apps import pagerank
    from lux_tpu.parallel.mesh import make_mesh

    g = _drill_graph()
    nd = min(4, len(jax.devices()))
    eng = pagerank.build_engine(g, num_parts=_DRILL_PARTS,
                                mesh=make_mesh(nd))
    ckpt = os.path.join(workdir, "drill.ckpt.npz")
    ev = telemetry.EventLog(os.path.join(workdir, "events.jsonl"))
    st = telemetry.IterStats()
    with telemetry.use(events=ev, iter_stats=st) as tel:
        tel.emit("run_start", schema=telemetry.SCHEMA, app="pagerank",
                 drill="worker_kill_solo")
        t0 = time.perf_counter()
        _state, _report = resilience.supervised_run(
            eng, _DRILL_NI, ckpt, segment=_DRILL_SEG, resume=True,
            policy=resilience.RetryPolicy(retries=0, jitter=0,
                                          sleep=lambda s: None))
        tel.emit("run_done",
                 seconds=round(time.perf_counter() - t0, 6),
                 iters=_DRILL_NI)
        _emit_iter_stats(tel, st)
        # the heartbeat protocol's shrink record, merged into the
        # same stream so the exporter shows the track transition
        tel.emit("mesh_shrink", protocol="heartbeat", from_nproc=2,
                 to_nproc=1, survivors=[0], generation=1)
    ev.close()
    print("DRILL_SOLO_OK", flush=True)
    return 0


def _drill_env() -> dict:
    """Subprocess env: CPU backend pinned BEFORE interpreter start
    and the axon site dropped (CLAUDE.md: sitecustomize imports jax
    at startup, so in-process env changes are too late)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    pp = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
          if p and ".axon_site" not in p]
    env["PYTHONPATH"] = os.pathsep.join([repo] + pp)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    return env


def run_kill_drill(workdir: str) -> str | None:
    """Drive the 2-subprocess worker-kill drill (2 processes x 4 CPU
    devices, one shared event file) and the degraded solo relaunch.
    Returns the merged events path, or None when this jaxlib's CPU
    backend cannot run multi-process collectives (caller falls back
    to the in-process DEVICE_LOSS drill)."""
    import socket
    import subprocess

    from lux_tpu import faults

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = _drill_env()
    nproc = 2
    procs = [subprocess.Popen(
        [sys.executable, "-m", "lux_tpu.tracing", "-drill-worker",
         str(i), str(nproc), str(port), workdir],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in range(nproc)]
    try:
        outs = [p.communicate(timeout=600)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(_CPU_MP_UNSUPPORTED.search(o) for o in outs):
        return None
    if procs[1].returncode != faults.HARD_KILL_CODE \
            or procs[0].returncode != 3:
        raise RuntimeError(
            f"worker-kill drill went off-script (rc="
            f"{[p.returncode for p in procs]}):\n" + "\n".join(outs))
    solo = subprocess.run(
        [sys.executable, "-m", "lux_tpu.tracing", "-drill-worker",
         "solo", "0", "0", workdir],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=600)
    if solo.returncode != 0:
        raise RuntimeError(f"solo relaunch failed:\n{solo.stdout}")
    return os.path.join(workdir, "events.jsonl")


def _drill_worker_main(argv) -> int:
    """Internal subprocess entry (python -m lux_tpu.tracing
    -drill-worker PID NPROC PORT WORKDIR; PID='solo' for the
    relaunch)."""
    pid, nproc, port, workdir = argv[0], int(argv[1]), argv[2], \
        argv[3]
    if pid == "solo":
        return _drill_worker_solo(workdir)
    return _drill_worker_distributed(int(pid), nproc, port, workdir)


def _summarize(trace, out_path, errs, to=sys.stdout) -> None:
    te = trace["traceEvents"]
    n_span = sum(1 for e in te if e.get("ph") == "X")
    n_inst = sum(1 for e in te if e.get("ph") == "i")
    marks = sorted({e["name"] for e in te if e.get("ph") == "i"})
    print(f"trace: {out_path}  "
          f"({trace['otherData']['streams']} stream(s), {n_span} "
          f"spans, {n_inst} instant markers)", file=to)
    if marks:
        print(f"  markers: {', '.join(marks)}", file=to)
    shrinks = [e for e in te
               if e.get("ph") == "i" and e["name"] == "mesh_shrink"]
    if shrinks:
        print(f"  mesh-shrink marker present "
              f"(x{len(shrinks)}) — load in chrome://tracing / "
              f"ui.perfetto.dev", file=to)
    print(("trace VALID (spans nest, no orphans)" if not errs
           else f"trace INVALID: {len(errs)} error(s)"), file=to)


def main(argv=None) -> int:
    import argparse

    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "-drill-worker":
        return _drill_worker_main(argv[1:])

    ap = argparse.ArgumentParser(
        prog="python -m lux_tpu.tracing",
        description="span timeline export (Chrome trace / Perfetto "
                    "JSON) from lux_tpu telemetry event logs; with "
                    "no FILE, runs the 4-app CPU smoke first")
    ap.add_argument("files", nargs="*", metavar="EVENTS_JSONL",
                    help="existing -events files to export (merged "
                         "onto one timeline)")
    ap.add_argument("-o", default="trace.json", dest="out",
                    metavar="TRACE_JSON")
    ap.add_argument("-drill", action="store_true",
                    help="run the 8-virtual-device elastic "
                         "worker-kill drill (2 subprocesses x 4 CPU "
                         "devices, shared event file, hard kill + "
                         "degraded relaunch) and export its merged "
                         "timeline; falls back to the in-process "
                         "DEVICE_LOSS drill where the CPU backend "
                         "has no multi-process collectives")
    ap.add_argument("-workdir", default=None,
                    help="working directory for drill/smoke "
                         "artifacts (default: a fresh temp dir)")
    ap.add_argument("-scale", type=int, default=8,
                    help="smoke RMAT scale (default 8)")
    ap.add_argument("-ef", type=int, default=8)
    ap.add_argument("-np", type=int, default=2, dest="np_parts")
    ap.add_argument("-apps", nargs="+", default=list(SMOKE_APPS),
                    choices=SMOKE_APPS, metavar="APP")
    args = ap.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="lux_trace_")
    os.makedirs(workdir, exist_ok=True)
    if args.files:
        # a rotated EventLog (rotate_bytes) leaves a .1/.2 generation
        # set beside the live file: consume the whole set, oldest
        # first, as one stream (telemetry.rotated_paths)
        paths = [g for p in args.files
                 for g in telemetry.rotated_paths(p)]
    elif args.drill:
        path = run_kill_drill(workdir)
        if path is None:
            print("# CPU backend has no multi-process collectives; "
                  "falling back to the in-process DEVICE_LOSS drill",
                  file=sys.stderr)
            # a FRESH file: the aborted workers' partial trails must
            # not merge into the fallback drill's timeline
            path = os.path.join(workdir, "events_loss.jsonl")
            run_loss_drill(workdir, path)
        paths = [path]
    else:
        path = os.path.join(workdir, "events.jsonl")
        run_smoke(path, apps=args.apps, scale=args.scale, ef=args.ef,
                  np_parts=args.np_parts)
        paths = [path]

    events, errs = [], []
    for p in paths:
        evs, es = load_events(p)
        events += evs
        errs += es
    trace = trace_export(events, out=args.out)
    verrs = validate_trace(trace)
    _summarize(trace, args.out, verrs)
    for e in errs + verrs:
        print(f"ERROR: {e}", file=sys.stderr)
    return 1 if (errs or verrs) else 0


if __name__ == "__main__":
    sys.exit(main())
