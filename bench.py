"""Benchmark driver: GTEPS per chip on the BASELINE.md configurations.

Methodology matches the reference (BASELINE.md): wall-clock around the
iteration loop only (graph generation/load/init excluded), GTEPS =
ne * iterations / elapsed_seconds / num_chips.  Graphs are R-MAT
(the reference's RMAT family, scaled to fit a single chip's HBM
comfortably at default settings).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GTEPS", "vs_baseline": N}
vs_baseline is against the north-star target of 1 GTEPS/chip
(BASELINE.json "north_star").

Configs (-config; default "pagerank" is what the driver records):
  pagerank        PageRank, pull model, fixed iterations   (BASELINE #1/#4)
  cc              Connected Components, push, to convergence (BASELINE #2)
  sssp            SSSP/BFS hops, push, to convergence        (BASELINE #3)
  colfilter       SGD matrix factorization, weighted pull    (BASELINE #5)
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def build_graph(args, weighted=False):
    import numpy as np

    from lux_tpu.convert import rmat_graph

    t0 = time.perf_counter()
    g = rmat_graph(scale=args.scale, edge_factor=args.ef, seed=0)
    if weighted:
        rng = np.random.default_rng(1)
        g.weights = rng.integers(1, 6, size=g.ne).astype(np.int32)
    if args.verbose:
        print(f"# graph built: nv={g.nv} ne={g.ne} "
              f"({time.perf_counter() - t0:.1f}s)", file=sys.stderr)
    return g


def bench_fused(eng, g, ni, verbose):
    import numpy as np

    from lux_tpu.timing import timed_fused_run

    t0 = time.perf_counter()
    state, elapsed = timed_fused_run(eng, ni)
    if verbose:
        print(f"# ran ({time.perf_counter() - t0:.1f}s total, "
              f"{elapsed:.2f}s timed)", file=sys.stderr)
    # the benched result must be sane, or the GTEPS line is meaningless
    assert np.isfinite(eng.unpad(state)).all(), "non-finite bench result"
    return g.ne * ni / elapsed


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-config", default="pagerank",
                    choices=["pagerank", "cc", "sssp", "colfilter"])
    ap.add_argument("-scale", type=int, default=0,
                    help="RMAT scale (nv = 2**scale; 0 = per-config "
                         "default)")
    ap.add_argument("-ef", type=int, default=16, help="edges per vertex")
    ap.add_argument("-ni", type=int, default=20,
                    help="iterations (fixed-iteration configs)")
    ap.add_argument("-np", type=int, default=1, help="partitions")
    ap.add_argument("-verbose", action="store_true")
    args = ap.parse_args()
    if not args.scale:
        args.scale = {"pagerank": 21, "cc": 20, "sssp": 21,
                      "colfilter": 18}[args.config]

    import numpy as np

    from lux_tpu.timing import timed_converge

    if args.config == "pagerank":
        from lux_tpu.apps import pagerank
        g = build_graph(args)
        if args.np == 1:
            # degree relabel + pair-lane delivery: dense tile pairs
            # skip the per-edge gather (ops/pairs.py; +40% measured)
            g2, _perm = pagerank.degree_relabel(g)
            eng = pagerank.build_engine(g2, num_parts=1,
                                        pair_threshold=16)
            if args.verbose and eng.pairs is not None:
                s = eng.pairs.stats
                print(f"# pair-lane coverage "
                      f"{s['coverage'] * 100:.1f}%", file=sys.stderr)
        else:
            eng = pagerank.build_engine(g, num_parts=args.np)
        gteps = bench_fused(eng, g, args.ni, args.verbose) / 1e9
        name = f"pagerank_rmat{args.scale}"
    elif args.config == "colfilter":
        from lux_tpu.apps import colfilter
        g = build_graph(args, weighted=True)
        eng = colfilter.build_engine(g, num_parts=args.np)
        gteps = bench_fused(eng, g, args.ni, args.verbose) / 1e9
        name = f"colfilter_rmat{args.scale}"
    else:
        from lux_tpu.apps import components, sssp
        g = build_graph(args)
        if args.config == "cc":
            # CC semantics need an undirected graph; symmetrize and
            # count the doubled edge set in GTEPS (it is what runs)
            from lux_tpu.graph import Graph
            s, d = components.symmetrize(*g.edge_arrays())
            g = Graph.from_edges(s, d, g.nv)
            if args.verbose:
                print(f"# symmetrized: ne={g.ne}", file=sys.stderr)
            eng = components.build_engine(g, num_parts=args.np)
        else:
            eng = sssp.build_engine(g, start_vertex=0,
                                    num_parts=args.np)
        labels, iters, elapsed = timed_converge(eng)
        if args.verbose:
            print(f"# converged in {iters} iterations, {elapsed:.2f}s",
                  file=sys.stderr)
        gteps = g.ne * iters / elapsed / 1e9
        name = f"{args.config}_rmat{args.scale}"

    result = {
        "metric": f"{name}_gteps_per_chip",
        "value": round(gteps, 4),
        "unit": "GTEPS",
        "vs_baseline": round(gteps / 1.0, 4),
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
