"""Benchmark driver: PageRank GTEPS per chip.

Methodology matches the reference (BASELINE.md): wall-clock around the
iteration loop only (graph generation/load/init excluded), GTEPS =
ne * iterations / elapsed_seconds / num_chips.  The graph is an R-MAT
(the reference's RMAT27 family, scaled to fit a single chip's HBM
comfortably at default settings).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GTEPS", "vs_baseline": N}
vs_baseline is against the north-star target of 1 GTEPS/chip
(BASELINE.json "north_star").
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-scale", type=int, default=21,
                    help="RMAT scale (nv = 2**scale)")
    ap.add_argument("-ef", type=int, default=16, help="edges per vertex")
    ap.add_argument("-ni", type=int, default=20, help="iterations to time")
    ap.add_argument("-np", type=int, default=1, help="partitions")
    ap.add_argument("-verbose", action="store_true")
    args = ap.parse_args()

    import jax
    import numpy as np

    from lux_tpu.apps import pagerank
    from lux_tpu.convert import rmat_edges
    from lux_tpu.graph import Graph

    t0 = time.perf_counter()
    src, dst, nv = rmat_edges(scale=args.scale, edge_factor=args.ef,
                              seed=0)
    g = Graph.from_edges(src, dst, nv)
    if args.verbose:
        print(f"# graph built: nv={g.nv} ne={g.ne} "
              f"({time.perf_counter() - t0:.1f}s)", file=sys.stderr)

    eng = pagerank.build_engine(g, num_parts=args.np)
    state = eng.init_state()

    def fetch(x):
        # On remote-tunnel TPU platforms block_until_ready can return
        # before execution finishes; a host fetch is the reliable fence.
        return float(np.asarray(jax.device_get(x)).ravel()[0])

    # Warmup with the SAME static iteration count (num_iters is a
    # static jit arg — a different count would recompile inside the
    # timed region), then reset state for the timed run.
    state = eng.run(state, args.ni)
    fetch(state)
    state = eng.init_state()
    if args.verbose:
        print(f"# compiled ({time.perf_counter() - t0:.1f}s)",
              file=sys.stderr)

    t1 = time.perf_counter()
    state = eng.run(state, args.ni)
    fetch(state)
    elapsed = time.perf_counter() - t1

    # Sanity: results must still match the oracle's magnitude.
    out = eng.unpad(state)
    assert np.isfinite(out).all()

    gteps = g.ne * args.ni / elapsed / 1e9
    result = {
        "metric": f"pagerank_rmat{args.scale}_gteps_per_chip",
        "value": round(gteps, 4),
        "unit": "GTEPS",
        "vs_baseline": round(gteps / 1.0, 4),
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
