"""Benchmark driver: GTEPS per chip on the BASELINE.md configurations.

Methodology matches the reference (BASELINE.md): wall-clock around the
iteration loop only (graph generation/load/init excluded), GTEPS =
ne * iterations / elapsed_seconds / num_chips.  Graphs are R-MAT
(the reference's RMAT family, scaled to fit a single chip's HBM
comfortably at default settings).

Prints ONE JSON line per benched config:
  {"metric": ..., "value": N, "unit": "GTEPS", "vs_baseline": N, ...}
vs_baseline is against the north-star target of 1 GTEPS/chip
(BASELINE.json "north_star").  Preprocessing that affects
comparability (degree relabel, pair-lane threshold, partitions) is
recorded in the line.

Variance discipline: the tunnel's run-to-run spread (0.095-0.127 on
identical binaries, PERF_NOTES) exceeds a whole round's optimization
gains, so every config runs the TIMED REGION ``-repeats`` times
(default 3; build/compile excluded) and reports the MEDIAN, with the
per-repeat samples recorded in the JSON line.

Telemetry (round 7, lux_tpu/telemetry.py): every config runs inside a
telemetry scope, and each metric line carries a ``telemetry`` field:
``runs`` (per-timed-run seconds + iteration counts, straight from the
``timed_run`` events — the per-sample decomposition that makes tunnel
variance auditable) and ``counters`` (the device-side per-iteration
counter digest when ``-iter-stats`` is on; null otherwise — counters
run a separate compiled variant of the loop, so they are opt-in for
the headline numbers).  ``-events FILE`` additionally appends the raw
event JSONL (rendered by scripts/events_summary.py);
scripts/check_bench.py validates the telemetry field against samples
and attempts.

Guarded execution (round 9, lux_tpu/health.py): ``-health`` runs
every config's timed loops under the device-side watchdog (NaN/Inf,
divergence/oscillation, frontier stalls — a separate compiled loop
variant, like the counter variants) and records the digest in each
line's ``telemetry.health`` (null when off); a tripped watchdog
fails the config with a _FAILED line.  scripts/check_bench.py
type-checks the digest.

Static audit (round 10, lux_tpu/audit.py): ``-audit`` (default
"warn") traces every config's compiled program variants at build time
and records the digest in each metric line's ``audit`` field — a
metric produced by a build that violates the framework's structural
invariants (two gathers in a dense iteration, a baked-in constant
past the 413 wall, a broken owner collective schedule...) is rejected
by scripts/check_bench.py, and ``-audit error`` refuses to run it at
all.

Resilience (round 6, lux_tpu/resilience.py): each config runs under
the supervisor — transient failures (worker death, tunnel drops)
retry with backoff up to ``-retries`` times, deterministic ones (OOM,
HTTP 413) fail the config immediately; and samples more than
``-outlier``x off their batch median (BENCH_r05's pagerank-mp
collapse: [0.1116, 0.0107, 0.1118]) are DISCARDED and re-run once
rather than silently medianed.  Every metric line records the audit
trail: "attempts" (total timed runs incl. outlier reruns),
"discarded" (the thrown-away samples), and "run_attempts" when the
whole config was retried.  scripts/check_bench.py validates the
schema.

Observatory (round 12, lux_tpu/observe.py): the session-calibration
probe runs once up front and every metric line carries its
``calibration`` digest (measured probe ns/elem vs the canonical
PERF_NOTES figures, platform, ndev, grade) — scripts/check_bench.py
REJECTS lines from "degraded" or "uncalibrated" sessions, so the 10x
tunnel-variance trap is detected and labeled instead of entering the
trajectory.  Every run also appends its lines to the persistent perf
ledger (``-ledger``, default PERFLEDGER.jsonl) and writes the
machine-readable BENCH_rNN.json artifact itself (``-json-out``,
default auto-numbered — the empty bench trajectory was a
hand-assembly gap, not a measurement gap).

Configs (-config runs one):
  pagerank        PageRank, pull model, fixed iterations   (BASELINE #1/#4)
  pagerank-mp     PageRank, np=4 multi-part OWNER exchange + pair
                  composition — the mesh-relevant path, regression-
                  guarded in the round artifact
  cc              Connected Components, push, to convergence (BASELINE #2)
  sssp            SSSP/BFS hops, push, to convergence        (BASELINE #3)
  sssp-delta      weighted SSSP, delta-stepping frontier     (BASELINE #3)
  colfilter       SGD matrix factorization, weighted pull    (BASELINE #5)

By DEFAULT every config runs (one JSON line each, pagerank LAST so a
line-parsing driver still records the headline metric as its tail
line).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from statistics import median

# The same preprocessing is applied at EVERY partition count so
# single-chip and multi-chip GTEPS stay apples-to-apples (round-1
# advice): degree relabel concentrates hubs into shared 128-vertex
# tiles, pair-lane delivery then serves dense tile pairs without the
# per-edge gather (ops/pairs.py, PERF_NOTES.md).
PAIR_THRESHOLD = 16   # default; override with -pair

# (scale, edge_factor) per config.  colfilter approximates the
# BASELINE NetFlix shape (497K vertices, ~400 ratings/vertex — dense):
# rmat16 x ef128 keeps the run short while staying density-faithful;
# the sparse rmat18 x ef16 shape it replaced is preserved in
# PERF_NOTES round-over-round tables.
DEFAULT_SHAPE = {"pagerank": (21, 16), "cc": (20, 16),
                 "sssp": (21, 16), "sssp-delta": (21, 16),
                 "colfilter": (16, 128), "pagerank-mp": (23, 16),
                 "sssp-mp": (23, 16),
                 # query-batched engines (ROADMAP item 2): k-source
                 # SSSP + personalized PageRank; `-config batch-sweep`
                 # expands over -batch (default B in {1, 8, 64}) and
                 # each line records batch + query_gteps = B x the
                 # machine rate — one gather serving B queries, so
                 # per-query delivered cost is 1/query_gteps ns/edge
                 "ksssp-batch": (20, 16), "ppr-batch": (20, 16),
                 # paged-vs-flat gather A/B (round 15,
                 # ops/pagegather.py): `-config gather-ab` runs
                 # pagerank BOTH ways on one degree-sorted graph and
                 # records the plan's measured unique-page ratio /
                 # row fill on both lines (scripts/check_bench.py
                 # validates the fields)
                 "gather-ab": (21, 16),
                 # MXU-vs-VPU reduce A/B (round 23, ops/tiled.py):
                 # `-config mxu-ab` runs the B=8 personalized-
                 # pagerank program (wide payload — the regime where
                 # the one-hot contraction amortizes, scalemodel.
                 # mxu_break_even_wide) BOTH ways on one degree-
                 # sorted community graph; each line carries the
                 # resolved mode + the modeled per-row reduce rates
                 # for both paths (scripts/check_bench.py validates
                 # mode-vs-name and the mxu/vpu pairing).  Community
                 # + degree sort keeps chunk rows dense (fill >= 23)
                 # so the per-row toll, not sparse-tail padding, is
                 # what the pair isolates.
                 "mxu-ab": (16, 64),
                 # serving-tier SLO lines (round 17, lux_tpu/serve.py
                 # + scripts/loadgen.py): `-config serve-slo` expands
                 # over -rates into one open-loop load step per
                 # offered rate; each line carries offered/achieved
                 # qps, snapshot p50/p99 and the SLO good fraction
                 # (scripts/check_bench.py rejects the contradictions:
                 # p99 < p50, achieved > offered, fraction outside
                 # [0, 1]).  The on-device run is carried as debt
                 # serve-slo-on-device (lux_tpu/observe.py).
                 "serve-slo": (12, 8),
                 # serving-tier chaos lines (round 18,
                 # lux_tpu/fleet.py): `-config serve-chaos` runs the
                 # serve-slo open-loop load against a FleetServer of
                 # -serve-replicas replicas with a ReplicaKillPlan
                 # armed post-warm; each line extends the serve-slo
                 # record with replicas/failovers/shed/shed_fraction
                 # plus (round 24, self-healing) respawns/
                 # quarantines/mttr_s/journal_replayed — the fleet
                 # runs with a durable admission journal and the
                 # resurrection supervisor armed (scripts/
                 # check_bench.py rejects the contradictions:
                 # shed_fraction outside [0,1], failovers or
                 # respawns with replicas=1, SLO accounting over
                 # shed queries, mttr without a fired kill,
                 # journal_replayed > submitted).  The real-TPU
                 # drill is debt serve-chaos-on-device.
                 "serve-chaos": (12, 8),
                 # live-graph serving lines (round 20,
                 # lux_tpu/livegraph.py): `-config serve-live` runs
                 # mixed-kind traffic against a MUTATING graph —
                 # WAL-free LiveGraph ingest between drains, per-
                 # column epoch pinning, the epoch-keyed answer
                 # cache, and at least one natural threshold-
                 # triggered compaction — and verifies EVERY answer
                 # against its NumPy oracle at the query's admission
                 # epoch before the line may print.  The line carries
                 # mutations/mutation_rate/epochs_advanced/
                 # compactions/cache_hit_fraction/peak_occupancy
                 # (scripts/check_bench.py rejects the
                 # contradictions: epochs advanced with zero
                 # mutations, hit fraction outside [0, 1], a
                 # compaction count with delta occupancy never past
                 # threshold).  The on-device run is carried as debt
                 # live-mutation-on-device (lux_tpu/observe.py).
                 "serve-live": (12, 8)}

# the batch-sweep expansion (one metric line per B per app)
BATCH_SWEEP_DEFAULT = "1,8,64"


def build_graph(scale, ef, verbose, weighted=False):
    import numpy as np

    from lux_tpu.convert import rmat_graph

    t0 = time.perf_counter()
    g = rmat_graph(scale=scale, edge_factor=ef, seed=0)
    if weighted:
        rng = np.random.default_rng(1)
        g.weights = rng.integers(1, 6, size=g.ne).astype(np.int32)
    if verbose:
        print(f"# graph built: nv={g.nv} ne={g.ne} "
              f"({time.perf_counter() - t0:.1f}s)", file=sys.stderr)
    return g


def _print_coverage(args, eng):
    if args.verbose and eng.pairs is not None:
        cov = eng.pairs.stats["coverage"]
        print(f"# pair-lane coverage {cov * 100:.1f}%", file=sys.stderr)


def _comm_build(eng, extra):
    """Round 19 (lux_tpu/comms.py): the per-collective byte ledger of
    the engine's step program — traced, oracle- and audit-cross-
    checked — lands in the metric line's ``comm`` field
    (comm_bytes_per_edge + the modeled comm_frac at this placement).
    A failing ledger records errors instead of a digest;
    scripts/check_bench.py rejects such lines, so a published number
    can never ride an un-accountable byte bill."""
    from lux_tpu import comms, observe

    try:
        led = comms.ledger_for(eng)
        model = observe._engine_model(eng, 1.0)
        compute_ns = sum(v for v in model.values() if v)
        extra["comm"] = comms.bench_digest(led, compute_ns=compute_ns)
    except Exception as e:  # noqa: BLE001 — a broken ledger must not
        # kill the run; the line records the failure and check_bench
        # rejects it from the trajectory
        extra["comm"] = {"errors": 1,
                         "error": f"{type(e).__name__}: {e}"[:200]}
        print(f"# comm ledger failed: {type(e).__name__}: {e}",
              file=sys.stderr)


def _mem_build(eng, extra, consumers=None, trail=None):
    """Round 22 (lux_tpu/memwatch.py): the runtime memory drift
    verdict of the engine's build — measured (or memory_analysis-
    modeled) peak vs the unified byte ledger — lands in the metric
    line's ``mem`` field.  A drifting or failing verdict records
    errors instead of a clean digest; scripts/check_bench.py rejects
    such lines, so a published number can never ride a build whose
    byte accounting has rotted."""
    from lux_tpu import memwatch

    try:
        extra["mem"] = memwatch.bench_digest(eng, trail=trail,
                                             consumers=consumers)
        if extra["mem"].get("errors"):
            print(f"# mem drift: {extra['mem']}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — a broken ledger must not
        # kill the run; the line records the failure and check_bench
        # rejects it from the trajectory
        extra["mem"] = {"errors": 1,
                        "error": f"{type(e).__name__}: {e}"[:200]}
        print(f"# mem ledger failed: {type(e).__name__}: {e}",
              file=sys.stderr)


def _audit_build(eng, args, extra):
    """Static program audit of the freshly built engine
    (lux_tpu/audit.py, round 10): traces every compiled loop variant
    — nothing executes, so the cost is size-independent — and records
    the digest in the metric line's ``audit`` field.
    scripts/check_bench.py REJECTS metric lines whose digest carries
    errors, so a benchmark number can never be published off a build
    that violates the framework's structural invariants; ``-audit
    error`` additionally fails the config at build time (typed
    AuditError, classified fatal).  Round 19: the comm byte ledger
    (``_comm_build``) rides the same hook — every engine metric line
    carries its ``comm`` digest regardless of the -audit mode.
    Round 22: the memory drift verdict (``_mem_build``) rides the
    same hook — every engine metric line carries its ``mem``
    digest."""
    _comm_build(eng, extra)
    _mem_build(eng, extra)
    if args.audit == "off":
        return
    from lux_tpu import audit

    findings = audit.audit_engine(eng, mode=None)
    d = audit.digest(findings, mode=args.audit)
    extra["audit"] = d
    if d["errors"] and args.audit == "error":
        audit.raise_findings(findings, where=type(eng).__name__)
    # findings print UNCONDITIONALLY: under the default 'warn' a
    # violating build would otherwise burn the whole benchmark run
    # silently and only be rejected by check_bench afterwards
    for f in findings:
        print(f"# audit: {f}", file=sys.stderr)


def bench_fused(eng, ne, ni, verbose, repeats):
    """GTEPS samples over ``repeats`` timed fused runs (ONE warmup/
    compile up front inside timed_fused_run; each repeat re-times only
    the fused loop).  Returns (samples, rerun) where ``rerun()`` times
    one more run (jit cache is warm) — the outlier discard-and-rerun
    rule's second chance."""
    import numpy as np

    from lux_tpu.timing import timed_fused_run

    t0 = time.perf_counter()
    state, elapsed = timed_fused_run(eng, ni, repeats=repeats)
    if verbose:
        times = " ".join(f"{e:.2f}s" for e in elapsed)
        print(f"# {repeats} timed runs ({time.perf_counter() - t0:.1f}s"
              f" total): {times}", file=sys.stderr)
    # the benched result must be sane, or the GTEPS line is meaningless
    assert np.isfinite(eng.unpad(state)).all(), "non-finite bench result"

    def rerun():
        _state, [e] = timed_fused_run(eng, ni, repeats=1)
        return ne * ni / e

    return [ne * ni / e for e in elapsed], rerun


def bench_converge(eng, ne, verbose, repeats):
    """GTEPS samples over ``repeats`` timed whole-run converges;
    returns (samples, rerun) like bench_fused."""
    from lux_tpu.timing import timed_converge

    labels, iters, elapsed = timed_converge(eng, repeats=repeats)
    if verbose:
        times = " ".join(f"{e:.2f}s" for e in elapsed)
        print(f"# converged in {iters} iterations; {repeats} timed "
              f"runs: {times}", file=sys.stderr)

    def rerun():
        _l, it, [e] = timed_converge(eng, repeats=1)
        return ne * it / e

    return [ne * iters / e for e in elapsed], rerun


def _rate_token(rate: float) -> str:
    return f"{rate:g}".replace(".", "p").replace("-", "m")


def run_serve_load(config, args, *, chaos: bool):
    """Shared body of the serve-slo and serve-chaos configs: one
    open-loop Poisson load step (scripts/loadgen.py) at the offered
    rate named by "<config>@RATE" against a mixed-kind
    continuous-batching server with per-kind latency SLOs.  The
    line's value/samples are the MEASURED achieved qps; offered/
    achieved, snapshot p50/p99, SLO targets and good fraction ride
    the line for scripts/check_bench.py's contradiction rejects
    (p99 < p50, achieved > offered, fraction outside [0, 1]).

    ``chaos`` (round 18, lux_tpu/fleet.py) swaps the single Server
    for a FleetServer of ``-serve-replicas`` replicas with a
    faults.ReplicaKillPlan armed AFTER the engine-compile warmup
    (the last replica dies at its ``-kill-boundary``-th loaded
    boundary), extends the line with replicas/failovers/shed/
    shed_fraction/slo_accounted, and FAILS unless the kill actually
    fired and at least one query failed over — a chaos line measured
    without chaos is a lie."""
    import itertools
    import os

    import numpy as np

    sdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "scripts")
    if sdir not in sys.path:
        sys.path.insert(0, sdir)
    import loadgen

    from lux_tpu import serve, telemetry

    family = "serve-chaos" if chaos else "serve-slo"
    _, _, rstr = config.partition("@")
    rate = float(rstr) if rstr else (60.0 if chaos else 20.0)
    if not rate > 0:
        # the bare-config expansion validates -rates; the @-form must
        # reject too, or a zero rate hangs the submitter forever
        raise ValueError(f"{family} offered rate must be > 0 qps, "
                         f"got {rate}")
    scale = args.scale or DEFAULT_SHAPE[family][0]
    ef = args.ef or DEFAULT_SHAPE[family][1]
    kinds = [k.strip() for k in args.serve_kinds.split(",")
             if k.strip()]
    slo = loadgen._parse_slo(args.slo_ms)
    g = build_graph(scale, ef, args.verbose)
    extra = {"np": args.np, "scale": scale, "ef": ef,
             "serve_batch": args.serve_batch, "kinds": kinds,
             "queries": args.serve_queries, "unit": "qps"}
    if chaos:
        from lux_tpu import faults, fleet, resilience
        if args.serve_replicas < 2:
            raise ValueError(
                "serve-chaos needs -serve-replicas >= 2: there is "
                "no surviving replica to fail over to with one")
        import tempfile
        # round 24: the chaos line exercises the SELF-HEALING tier —
        # admissions journaled durably (the line reports how many a
        # recovery would replay: 0 on a drained run) and the killed
        # replica resurrected under backoff with canary-gated
        # routing re-entry (respawns/quarantines/mttr_s ride the
        # line; check_bench rejects the contradictions)
        jpath = os.path.join(tempfile.mkdtemp(prefix="lux_chaos_j_"),
                             "admissions.journal")
        srv = fleet.FleetServer(
            g, replicas=args.serve_replicas, batch=args.serve_batch,
            num_parts=args.np, seg_iters=2, slo_ms=slo,
            health=args.health,
            retry=resilience.RetryPolicy(retries=3, backoff_s=0.01,
                                         max_backoff_s=0.1,
                                         jitter_seed=0),
            journal_path=jpath, heal=True,
            respawn_retry=resilience.RetryPolicy(
                retries=3, backoff_s=0.01, max_backoff_s=0.1,
                jitter_seed=1))
        runner_of = srv._replicas[0].runner
        extra["replicas"] = args.serve_replicas
    else:
        srv = serve.Server(g, batch=args.serve_batch,
                           num_parts=args.np, seg_iters=2,
                           slo_ms=slo, health=args.health)
        runner_of = srv._runner
    if args.audit != "off":
        from lux_tpu import audit
        findings = []
        for k in kinds:
            findings += audit.audit_engine(runner_of(k).eng,
                                           mode=None)
        d = audit.digest(findings, mode=args.audit)
        extra["audit"] = d
        if d["errors"] and args.audit == "error":
            audit.raise_findings(findings, where=family)
        for f in findings:
            print(f"# audit: {f}", file=sys.stderr)
    # compile outside the load — the fleet warms EVERY (replica,
    # kind) engine (routing-spread warm would leave cold runners
    # whose first measured query pays XLA compilation)
    if chaos:
        srv.warm(kinds)
        # arm the kill AFTER warm so its boundary counter sees only
        # loaded traffic — and on the replica routing WILL pick
        # (fleet.routing_target): routing is a positive-feedback
        # loop (drain -> fresh beat -> picked again), so a plan
        # armed on any fixed index is a coin flip on beat timing
        # inside warm, and the losing side is a chaos line that
        # silently measured a fault-free run (the round-22 fix;
        # the regression test pins it)
        victim = srv.routing_target(kinds[0])
        srv.set_fault(faults.ReplicaKillPlan(
            {victim: args.kill_boundary}))
    else:
        loadgen.warm(srv, kinds)
    rng = np.random.default_rng(7)   # fixed seed: one query schedule
    steps = itertools.count()

    def one_step():
        step = next(steps)
        rep = loadgen.run_step(srv, rate, args.serve_queries, kinds,
                               rng, step=step)
        telemetry.current().emit("timed_run", repeat=step,
                                 iters=rep.served,
                                 seconds=round(rep.elapsed_s, 6))
        if not rep.drained:
            raise RuntimeError(
                f"{family} load step {step} did not drain "
                f"({rep.served}+{rep.shed}/{rep.submitted})")
        if rep.slo_good_fraction is None or rep.p50_ms is None:
            raise RuntimeError(
                f"{family} load step {step} produced no SLO "
                f"accounting (slo_ms={slo!r})")
        return rep

    rep = one_step()
    # round 22: the serving line's mem digest — one drained engine's
    # drift verdict widened by the dynamic consumer terms (cache is
    # absent on these configs; the digest still prices the engine)
    from lux_tpu import memwatch
    _mem_build(runner_of(kinds[0]).eng, extra,
               consumers=memwatch.consumer_terms(
                   cache=getattr(srv, "cache", None),
                   live=getattr(srv, "live", None)))
    if chaos and (not srv.fault.fired or srv.failovers < 1):
        raise RuntimeError(
            "serve-chaos kill plan never fired (or nothing failed "
            "over) — the chaos line would be measuring a fault-free "
            "run")
    if chaos and srv.respawns + srv.quarantines < 1:
        # heal-armed run() does not return until every lost replica
        # resurrected or quarantined, so a fired kill with neither
        # means the healing tier silently did not engage
        raise RuntimeError(
            "serve-chaos kill fired but the healing supervisor "
            "neither respawned nor quarantined the replica")
    if args.verbose:
        loadgen.render_table([rep], out=sys.stderr)
    extra.update(offered_qps=round(rep.offered_qps, 4),
                 achieved_qps=round(rep.achieved_qps, 4),
                 p50_ms=round(rep.p50_ms, 4),
                 p99_ms=round(rep.p99_ms, 4),
                 slo_target_ms=slo,
                 slo_good_fraction=round(rep.slo_good_fraction, 4),
                 served=rep.served, submitted=rep.submitted)
    if chaos:
        extra.update(failovers=int(srv.failovers),
                     shed=int(rep.shed),
                     shed_fraction=round(rep.shed
                                         / max(1, rep.submitted), 4),
                     slo_accounted=rep.slo_accounted,
                     # round-24 healing gauges: resurrections that
                     # re-entered routing (canary-gated), typed
                     # quarantines, repair time (first loss -> pool
                     # whole; None when the pool never re-completed),
                     # and how many admitted-unretired queries a
                     # crash recovery would re-dispatch NOW (a
                     # drained run retired everything: 0)
                     respawns=int(srv.respawns),
                     quarantines=int(srv.quarantines),
                     mttr_s=(None if srv.mttr_s is None
                             else round(srv.mttr_s, 4)),
                     journal_replayed=int(srv.journal_replayed))
    prefix = "serve_chaos" if chaos else "serve_slo"
    name = f"{prefix}_q{_rate_token(rate)}_rmat{scale}"
    return (name, [rep.achieved_qps], extra,
            lambda: one_step().achieved_qps)


def run_serve_live(config, args):
    """The live-graph serving line (rounds 20-22,
    lux_tpu/livegraph.py): mixed-kind traffic over a MUTATING
    WEIGHTED graph exercising the FULL mutation algebra — appends,
    deletions + the honest re-seed, and (round 22) per-phase
    REWEIGHTS, the algebra leg an unweighted headline structurally
    reported as reweights=0.  Each phase appends first (one
    published epoch), then drains two query waves — the second wave
    repeats the first's hot sources at the SAME epoch, so the
    epoch-keyed answer cache measurably hits.  Two of the phases
    DELETE a previously-appended edge and run the honest
    anti-monotone re-seed (a converged pre-deletion state repaired
    to the published epoch on a standalone engine over
    ``graph_at(target)``, exactly equal to the full recompute —
    integer-valued f32 weights keep the comparison exact);
    compaction is decided by the round-21
    CompactionScheduler (anti-monotone pressure / occupancy / drag
    economics) instead of the bare occupancy heuristic, with
    Server.refresh_live generation adoption between drains.  EVERY
    answer is verified against its NumPy oracle at the query's
    admission epoch before the line may print — a wrong answer is a
    crash, never a published number.  check_bench rejects the line's
    contradictions, round-21 algebra fields included (see
    DEFAULT_SHAPE comment)."""
    import os
    import time as _time

    import numpy as np

    from lux_tpu import livegraph, serve, telemetry
    from lux_tpu.apps import sssp as _sssp

    sdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "scripts")
    if sdir not in sys.path:
        sys.path.insert(0, sdir)
    import loadgen

    scale = args.scale or DEFAULT_SHAPE["serve-live"][0]
    ef = args.ef or DEFAULT_SHAPE["serve-live"][1]
    kinds = [k.strip() for k in args.serve_kinds.split(",")
             if k.strip()]
    slo = loadgen._parse_slo(args.slo_ms)
    # round 22: the headline line is WEIGHTED — integer-valued f32
    # weights (1..5) keep every device f32 distance exact, so the
    # weighted oracle checks and the honest re-seed stay exact
    # comparisons, and the line's reweight counter measures the one
    # algebra leg (round 21) the unweighted line structurally
    # couldn't (reweights=0 forever)
    g = build_graph(scale, ef, args.verbose, weighted=True)
    capacity = args.delta_capacity

    def build_tier():
        """ONE construction for sample 0 and every rerun — the two
        must measure the identical workload (live graph shape, cache
        policy, scheduler cadence), so there is exactly one place
        to tune it."""
        from lux_tpu import memwatch
        lv = livegraph.LiveGraph(g, capacity=capacity,
                                 compact_threshold=0.75)
        sv = serve.Server(g, batch=args.serve_batch,
                          num_parts=args.np, seg_iters=2, slo_ms=slo,
                          health=args.health, weighted=True,
                          live=lv, cache=True)
        # round 22: the runtime occupancy trail rides the drain —
        # boundary-only samples (measured free, PERF_NOTES round 22)
        # over the unified server ledger, so the events trail carries
        # the mem_sample/mem_watermark series events_summary renders
        sv.mem = memwatch.MemoryTrail(
            bytes_fn=lambda: memwatch.MemoryLedger
            .for_server(sv).total_bytes, emit_every=4)
        sc = livegraph.CompactionScheduler(lv, burn=sv.slo_burn)
        return lv, sv, sc

    live, srv, sched = build_tier()
    extra = {"np": args.np, "scale": scale, "ef": ef,
             "serve_batch": args.serve_batch, "kinds": kinds,
             "unit": "qps", "delta_capacity": capacity,
             "compact_threshold": live.compact_threshold}
    if args.audit != "off":
        from lux_tpu import audit
        findings = []
        for k in kinds:
            eng = srv._runner(k).eng
            if k in ("sssp", "components"):
                # the live delta-relax step rides the same audited
                # gather budget as the dense iterations
                live.register_audit(eng)
            findings += audit.audit_engine(eng, mode=None)
        d = audit.digest(findings, mode=args.audit)
        extra["audit"] = d
        if d["errors"] and args.audit == "error":
            audit.raise_findings(findings, where="serve-live")
        for f in findings:
            print(f"# audit: {f}", file=sys.stderr)
    loadgen.warm(srv, kinds)
    nv = g.nv
    phases = 6
    per = max(len(kinds), args.serve_queries // (2 * phases))
    # mutation volume sized to cross the compact threshold mid-run:
    # phases-1 batches of ceil(threshold*cap/(phases-2)) edges pass
    # 0.75*cap at phase ~ phases-2, leaving >= 1 natural compaction
    per_mut = int(np.ceil(live.compact_threshold * capacity
                          / max(1, phases - 2)))

    delete_phases = (2, 4)

    def reseed_honest(lv, target):
        """The HONEST anti-monotone re-seed: converge over the
        pre-deletion snapshot, repair that state to ``target`` on a
        standalone engine built over ``graph_at(target)`` (the
        revalidate contract), and refuse the line unless the result
        is exactly the full recompute — the weighted line's
        integer-valued f32 weights make every finite distance exact,
        so this stays an equality check, not a tolerance."""
        import jax

        pre = lv.graph_at(target - 1)
        eng0 = _sssp.build_engine(pre, 0, num_parts=args.np,
                                  weighted=True)
        lab, act = eng0.init_state()
        lab, act, _ = eng0.converge(lab, act)
        host = eng0.sg.from_padded(np.asarray(jax.device_get(lab)))
        g_t = lv.graph_at(target)
        eng1 = _sssp.build_engine(g_t, 0, num_parts=args.np,
                                  weighted=True)
        lab1, act1 = eng1.place(
            eng1.sg.to_padded(host),
            eng1.sg.to_padded(np.zeros(nv, bool)))
        lab1, act1, _ = lv.revalidate(eng1, lab1, act1)
        got = eng1.sg.from_padded(
            np.asarray(jax.device_get(lab1)))
        ref = _sssp.reference_sssp(g_t, 0, weighted=True)
        fin_g, fin_r = np.isfinite(got), np.isfinite(ref)
        if not (np.array_equal(fin_g, fin_r)
                and np.array_equal(
                    got[fin_g].astype(np.float64),
                    ref[fin_r].astype(np.float64))):
            raise RuntimeError(
                "serve-live: the anti-monotone re-seed differs from "
                "the full recompute at its target epoch — a wrong "
                "repair must never print a line")

    def load_phase(lv, sv, sc, rng, phase, tracked):
        """One phase: append (tracking an edge for later deletion),
        on the deletion phases delete a tracked edge + run the
        honest re-seed, on the others REWEIGHT the newest tracked
        edge (the round-21 algebra leg an unweighted line cannot
        carry), then two query waves — the repeat wave is the
        cache-hit traffic.  The scheduler alone decides folds at the
        phase boundary.  Returns (responses, submitted)."""
        s_new = rng.integers(nv, size=per_mut)
        d_new = rng.integers(nv, size=per_mut)
        w_new = rng.integers(1, 6, size=per_mut).astype(np.float32)
        sv.mutate(s_new, d_new, w_new)
        tracked.append((int(s_new[0]), int(d_new[0])))
        if phase in delete_phases and len(tracked) > 1:
            es, ed = tracked.pop(0)
            sv.mutate([es], [ed], op="delete")
            reseed_honest(lv, lv.epoch)
        elif phase and tracked:
            rs, rd = tracked[-1]
            sv.mutate([rs], [rd],
                      weights=[float(rng.integers(1, 6))],
                      op="reweight")
        hot = {k: int(rng.integers(nv)) for k in kinds}
        n = 0
        out = []
        for wave in range(2):
            for i in range(per):
                kind = kinds[i % len(kinds)]
                s = hot[kind] if i < len(kinds) \
                    else int(rng.integers(nv))
                sv.submit(kind, source=s)
                n += 1
            out += sv.run()
        sc.maybe_compact(server=sv)
        return out, n

    def one_step(lv, sv, sc):
        rng = np.random.default_rng(7)
        t0 = _time.monotonic()
        responses, submitted = [], 0
        tracked = []
        for phase in range(phases):
            out, n = load_phase(lv, sv, sc, rng, phase, tracked)
            responses += out
            submitted += n
        elapsed = _time.monotonic() - t0
        bad = livegraph.check_live_answers(lv, responses,
                                           weighted=True)
        if bad:
            raise RuntimeError(
                f"serve-live: {bad} answer(s) differ from the NumPy "
                f"oracle at their admission epochs — a wrong-answer "
                f"line must never print")
        telemetry.current().emit("timed_run", repeat=0,
                                 iters=len(responses),
                                 seconds=round(elapsed, 6))
        return len(responses) / elapsed, elapsed, submitted

    def fresh_run():
        """A rerun must measure the SAME workload as the sample it
        replaces — mutation stream, deletions + re-seeds, scheduler
        folds, cold answer cache — so it rebuilds the tier
        (build_tier, the one shared construction) and replays the
        identical seeded traffic.  The jit cache is warm (same
        shapes), so no compile cost recurs; replaying more queries
        over the now-static mutated graph instead would skip the
        very mutation/compaction path this line claims to time."""
        lv, sv, sc = build_tier()
        loadgen.warm(sv, kinds)
        return one_step(lv, sv, sc)[0]

    qps, elapsed, submitted = one_step(live, srv, sched)
    hit_frac = srv.cache.hit_fraction() or 0.0
    # round 22: the live line's mem digest prices the full unified
    # ledger — engine terms + the REAL post-run consumer bytes
    # (answer cache, delta blocks, WAL, multiset, staging)
    from lux_tpu import memwatch
    _mem_build(srv._runner(kinds[0]).eng, extra,
               consumers=memwatch.consumer_terms(cache=srv.cache,
                                                 live=live))
    if live.compactions < 1:
        raise RuntimeError(
            "serve-live: no compaction fired — the line would not "
            "measure the generation-swap path it claims to")
    if live.deletions < 1 or live.reseeds < 1:
        raise RuntimeError(
            "serve-live: the deletion/re-seed phases did not run — "
            "the line would not measure the mutation algebra it "
            "claims to")
    if live.reweights < 1:
        raise RuntimeError(
            "serve-live: no reweight ran — the weighted line would "
            "not measure the algebra leg it exists to carry")
    extra.update(
        weighted=True,
        submitted=submitted,
        served=submitted,
        mutations=int(live.mutations),
        mutation_rate_per_s=round(live.mutations / elapsed, 4),
        epochs_advanced=int(live.epoch),
        compactions=int(live.compactions),
        deletions=int(live.deletions),
        reweights=int(live.reweights),
        reseeds=int(live.reseeds),
        scheduler_compactions=int(sched.scheduler_compactions),
        cache_hit_fraction=round(hit_frac, 4),
        peak_occupancy=round(live.peak_count / capacity, 4))
    name = f"serve_live_rmat{scale}"
    return (name, [qps], extra, fresh_run)


def run_config(config, args):
    """Returns (name, gteps samples list, extra json fields,
    rerun() -> one more gteps sample)."""
    pair_t = args.pair if args.pair > 0 else None
    import numpy as np

    from lux_tpu.graph import pair_relabel

    if config.startswith("serve-slo"):
        return run_serve_load(config, args, chaos=False)

    if config.startswith("serve-chaos"):
        return run_serve_load(config, args, chaos=True)

    if config.startswith("serve-live"):
        return run_serve_live(config, args)

    if config.startswith("gather-ab"):
        # paged-vs-flat A/B: "gather-ab@paged[:reorder]" names one
        # side + preprocessing each; all sides run the SAME base
        # graph, so the pairs are directly comparable.  The reorder
        # token (round 16, lux_tpu/reorder.py) swaps the degree sort
        # for the page-aware pass and records it in the line's
        # ``reorder`` field (scripts/check_bench.py validates
        # mode-vs-name AND fill-not-decreased vs the paired none
        # line).
        from lux_tpu.apps import pagerank
        from lux_tpu.graph import ShardedGraph, degree_relabel
        from lux_tpu.ops.pagegather import plan_paged_stats

        _, _, spec = config.partition("@")
        mode, _, reorder = (spec or "paged").partition(":")
        reorder = reorder or "none"
        scale = args.scale or DEFAULT_SHAPE["gather-ab"][0]
        ef = args.ef or DEFAULT_SHAPE["gather-ab"][1]
        shape = getattr(args, "shape", "rmat")
        if shape == "community":
            from lux_tpu.convert import community_graph
            t0 = time.perf_counter()
            g = community_graph(scale=scale, edge_factor=ef)
            if args.verbose:
                print(f"# community graph built: nv={g.nv} ne={g.ne}"
                      f" ({time.perf_counter() - t0:.1f}s)",
                      file=sys.stderr)
        else:
            g = build_graph(scale, ef, args.verbose)
        if reorder == "none":
            # degree sort concentrates hubs into shared pages — the
            # round-15 baseline preprocessing, kept for the paired
            # none lines so reorder gains are measured against it
            g2, _perm = degree_relabel(g)
        else:
            from lux_tpu.reorder import page_reorder
            g2, _perm, rep = page_reorder(g, method=reorder,
                                          num_parts=args.np,
                                          verbose=args.verbose)
            if args.verbose:
                print(f"# reorder {reorder}: padded_fill "
                      f"{rep['baseline_fill']} -> "
                      f"{rep['chosen_fill']}", file=sys.stderr)
        sg = ShardedGraph.build(g2, args.np, vpad_align=128)
        eng = pagerank.build_engine(g2, num_parts=args.np, sg=sg,
                                    gather=mode, health=args.health)
        # the recorded page stats come from the SAME counting pass
        # for every side (dense paged shape) — the exact objective
        # the reorder pass maximizes — so paired lines compare one
        # quantity regardless of delivery mode or the engine's
        # resolved exchange (a pagemajor plan's virtual fill or an
        # owner-shaped fill would break check_bench's
        # fill-not-decreased pairing rule on a correct run)
        stats = plan_paged_stats(sg)
        extra = {"np": args.np, "scale": scale, "ef": ef,
                 "relabel": True, "pair_threshold": None,
                 "gather": mode, "exchange": eng.exchange,
                 "reorder": reorder, "shape": shape,
                 "page_ratio": round(float(stats["page_ratio"]), 4),
                 # the PADDED fill — live lanes per padded row, the
                 # exact input gather="auto" and the phase model
                 # consume (class-pad rows pay full machinery)
                 "page_fill": round(float(stats["padded_fill"]), 2)}
        _audit_build(eng, args, extra)
        samples, rerun = bench_fused(eng, g.ne, args.ni, args.verbose,
                                     args.repeats)
        extra["ne"] = int(g.ne)
        tag = "comm" if shape == "community" else "rmat"
        rtok = "" if reorder == "none" else f"{reorder}_"
        return (f"pagerank_{mode}_{rtok}{tag}{scale}",
                [s / 1e9 for s in samples], extra,
                lambda: rerun() / 1e9)

    if config.startswith("mxu-ab"):
        # MXU-vs-VPU reduce A/B (round 23, ops/tiled.py):
        # "mxu-ab@mxu" / "mxu-ab@vpu" name one reduce path each; both
        # sides run the SAME degree-sorted community graph and the
        # SAME B=8-column personalized-pagerank program (the wide
        # payload is where the one-hot contraction amortizes its
        # ~160 ns materialization toll — scalemodel.
        # mxu_break_even_wide), so the pair isolates the chunk-row
        # reduce and nothing else.  Every line records the engine's
        # RESOLVED mode plus the scalemodel per-row rates for BOTH
        # paths (the modeled step-change); scripts/check_bench.py
        # validates mode-vs-name and rejects an mxu line whose
        # paired vpu baseline is missing from the artifact.  The
        # real-TPU run is debt mxu-core-ab (lux_tpu/observe.py).
        from lux_tpu import scalemodel
        from lux_tpu.apps import pagerank
        from lux_tpu.convert import community_graph
        from lux_tpu.graph import ShardedGraph, degree_relabel
        from lux_tpu.ops.pagegather import plan_paged_stats

        _, _, mode = config.partition("@")
        mode = mode or "mxu"
        if mode not in ("mxu", "vpu"):
            raise ValueError(f"mxu-ab side must be mxu|vpu, "
                             f"got {mode!r}")
        scale = args.scale or DEFAULT_SHAPE["mxu-ab"][0]
        ef = args.ef or DEFAULT_SHAPE["mxu-ab"][1]
        t0 = time.perf_counter()
        g = community_graph(scale=scale, edge_factor=ef)
        if args.verbose:
            print(f"# community graph built: nv={g.nv} ne={g.ne}"
                  f" ({time.perf_counter() - t0:.1f}s)",
                  file=sys.stderr)
        g2, _perm = degree_relabel(g)
        sg = ShardedGraph.build(g2, args.np, vpad_align=128)
        # fixed-seed sources: every side (and every round) serves the
        # same query set; B=8 matches the flagship auto-engagement
        # audit config (ppr_np2_batched)
        B = 8
        rng = np.random.default_rng(23)
        sources = sorted(int(x) for x in
                         rng.choice(g2.nv, size=B, replace=False))
        eng = pagerank.build_engine(g2, num_parts=args.np, sg=sg,
                                    sources=sources,
                                    use_mxu=(mode == "mxu"),
                                    health=args.health)
        stats = plan_paged_stats(sg)
        kind = getattr(eng.program, "reduce", "sum")
        extra = {"np": args.np, "scale": scale, "ef": ef,
                 "relabel": True, "pair_threshold": None,
                 "batch": B, "shape": "community",
                 "mxu": mode, "use_mxu": bool(eng.use_mxu),
                 "exchange": eng.exchange, "reduce_kind": kind,
                 # the modeled per-chunk-row rates for BOTH paths —
                 # identical on the paired lines by construction, so
                 # the pair's measured ratio is read against ONE
                 # prediction (scalemodel round 23)
                 "mxu_row_ns": round(scalemodel.mxu_reduce_row_ns(
                     wide=B, kind=kind), 2),
                 "vpu_row_ns": round(scalemodel.vpu_reduce_row_ns(
                     wide=B), 2),
                 "page_fill": round(float(stats["padded_fill"]), 2)}
        _audit_build(eng, args, extra)
        samples, rerun = bench_fused(eng, g.ne, args.ni, args.verbose,
                                     args.repeats)
        extra["ne"] = int(g.ne)
        return (f"ppr_{mode}_comm{scale}",
                [s / 1e9 for s in samples], extra,
                lambda: rerun() / 1e9)

    if config.startswith(("ksssp-batch", "ppr-batch")):
        # query-batched configs (ROADMAP item 2): "<base>@B" names
        # one sweep point — handled BEFORE the generic shape lookup
        # (DEFAULT_SHAPE is keyed by the base name, not "@B").
        # Sources are a fixed-seed draw so every sweep point (and
        # every round) serves the same query set; pair delivery is
        # scalar-state and stays off.
        base, _, bstr = config.partition("@")
        B = int(bstr) if bstr else 8
        scale = args.scale or DEFAULT_SHAPE[base][0]
        ef = args.ef or DEFAULT_SHAPE[base][1]
        extra = {"np": args.np, "scale": scale, "ef": ef}
        g = build_graph(scale, ef, args.verbose)
        rng = np.random.default_rng(7)
        sources = sorted(int(x) for x in
                         rng.choice(g.nv, size=B, replace=False))
        if base == "ksssp-batch":
            from lux_tpu.apps import sssp
            eng = sssp.build_engine(g, sources=sources,
                                    num_parts=args.np,
                                    health=args.health)
            extra.update(batch=B, relabel=False, pair_threshold=None,
                         exchange=eng.exchange)
            _audit_build(eng, args, extra)
            samples, rerun = bench_converge(eng, g.ne, args.verbose,
                                            args.repeats)
            name = f"ksssp_b{B}_rmat{scale}"
        else:
            from lux_tpu.apps import pagerank
            eng = pagerank.build_engine(g, num_parts=args.np,
                                        sources=sources,
                                        health=args.health)
            extra.update(batch=B, relabel=False, pair_threshold=None,
                         exchange=eng.exchange)
            _audit_build(eng, args, extra)
            samples, rerun = bench_fused(eng, g.ne, args.ni,
                                         args.verbose, args.repeats)
            name = f"ppr_b{B}_rmat{scale}"
        extra["ne"] = int(g.ne)
        return (name, [s / 1e9 for s in samples], extra,
                lambda: rerun() / 1e9)

    scale = args.scale or DEFAULT_SHAPE[config][0]
    ef = args.ef or DEFAULT_SHAPE[config][1]
    extra = {"np": args.np, "scale": scale, "ef": ef}

    if config in ("pagerank", "pagerank-mp"):
        from lux_tpu.apps import pagerank
        # pagerank-mp: the multi-part OWNER-exchange path (+ pair
        # composition) — the mesh-relevant configuration, regression-
        # guarded in the round artifact (round-3 VERDICT weak #2).
        # The scale-23 table (34 MB) sits under the auto threshold, so
        # the exchange is pinned explicitly.
        mp = config == "pagerank-mp"
        np_parts = max(args.np, 4) if mp else args.np
        g = build_graph(scale, ef, args.verbose)
        g2, _perm, starts = pair_relabel(g, np_parts,
                                         pair_threshold=pair_t or 16)
        eng = pagerank.build_engine(g2, num_parts=np_parts,
                                    pair_threshold=pair_t,
                                    pair_min_fill=args.min_fill,
                                    starts=starts,
                                    exchange="owner" if mp else "auto",
                                    health=args.health)
        extra.update(relabel=True, pair_threshold=pair_t, np=np_parts,
                     exchange=eng.exchange, min_fill=args.min_fill)
        _audit_build(eng, args, extra)
        _print_coverage(args, eng)
        samples, rerun = bench_fused(eng, g.ne, args.ni, args.verbose,
                                     args.repeats)
        name = f"pagerank{'_mp' if mp else ''}_rmat{scale}"
    elif config == "colfilter":
        from lux_tpu.apps import colfilter
        g = build_graph(scale, ef, args.verbose, weighted=True)
        if pair_t is not None:
            g2, _perm, starts = pair_relabel(g, args.np,
                                             pair_threshold=pair_t)
            eng = colfilter.build_engine(g2, num_parts=args.np,
                                         pair_threshold=pair_t,
                                         pair_min_fill=args.min_fill_dot,
                                         starts=starts,
                                         health=args.health)
            extra.update(relabel=True, pair_threshold=pair_t,
                         min_fill=args.min_fill_dot)
        else:
            eng = colfilter.build_engine(g, num_parts=args.np,
                                         health=args.health)
            extra.update(relabel=False, pair_threshold=None)
        _audit_build(eng, args, extra)
        _print_coverage(args, eng)
        samples, rerun = bench_fused(eng, g.ne, args.ni, args.verbose,
                                     args.repeats)
        name = f"colfilter_rmat{scale}"
    else:
        from lux_tpu.apps import components, sssp
        weighted = config == "sssp-delta"
        g = build_graph(scale, ef, args.verbose, weighted=weighted)
        if config == "cc":
            # CC semantics need an undirected graph; symmetrize and
            # count the doubled edge set in GTEPS (it is what runs)
            from lux_tpu.graph import Graph
            s, d = components.symmetrize(*g.edge_arrays())
            g = Graph.from_edges(s, d, g.nv)
            if args.verbose:
                print(f"# symmetrized: ne={g.ne}", file=sys.stderr)
            g2, _perm, starts = pair_relabel(g, args.np, pair_threshold=pair_t or 16)
            eng = components.build_engine(g2, num_parts=args.np,
                                          pair_threshold=pair_t,
                                          pair_min_fill=args.min_fill,
                                          starts=starts,
                                          health=args.health)
            extra.update(relabel=True, pair_threshold=pair_t,
                         min_fill=args.min_fill)
        else:
            # sssp-mp: the PUSH engine's mesh-relevant path — np=4
            # owner-side dense iterations + sparse queues, regression-
            # guarded like pagerank-mp (round-4 VERDICT #7).  The
            # scale-23 int32 label table (34 MB) sits under the auto
            # threshold, so the exchange is pinned explicitly.
            mp = config == "sssp-mp"
            np_parts = max(args.np, 4) if mp else args.np
            g2, perm, starts = pair_relabel(g, np_parts,
                                            pair_threshold=pair_t or 16)
            rank = np.empty(g.nv, np.int64)
            rank[perm] = np.arange(g.nv)
            eng = sssp.build_engine(
                g2, start_vertex=int(rank[0]), num_parts=np_parts,
                weighted=weighted,
                delta="auto" if config == "sssp-delta" else None,
                pair_threshold=pair_t, pair_min_fill=args.min_fill,
                starts=starts,
                exchange="owner" if mp else "auto",
                health=args.health)
            extra.update(relabel=True, pair_threshold=pair_t,
                         min_fill=args.min_fill, np=np_parts,
                         exchange=eng.exchange,
                         delta="auto" if weighted else None)
        _audit_build(eng, args, extra)
        _print_coverage(args, eng)
        samples, rerun = bench_converge(eng, g.ne, args.verbose,
                                        args.repeats)
        name = f"{config.replace('-', '_')}_rmat{scale}"
    # ne as it RAN (post-symmetrize for cc): lets check_bench re-derive
    # each sample from the telemetry runs' (iters, seconds)
    extra["ne"] = int(g.ne)
    return (name, [s / 1e9 for s in samples], extra,
            lambda: rerun() / 1e9)


def emit(name, samples, extra, attempts=None, discarded=(),
         telemetry=None, calibration=None):
    """One JSON metric line.  attempts = total timed runs (originals
    + outlier reruns); discarded = samples thrown out by the >3x rule
    — recorded, never silently medianed; telemetry = per-run seconds
    + counter digest; calibration = the session-calibration
    fingerprint digest (lux_tpu/observe.py — labels the line with
    this process's measured probe rate so a degraded tunnel session
    is detected, not medianed).  scripts/check_bench.py validates
    all of it.  Returns the line dict (artifact/ledger writers)."""
    gteps = median(samples)
    # serve-slo lines are qps, not GTEPS — the unit names the metric
    # suffix so the two families can never be conflated by name
    unit = extra.get("unit", "GTEPS")
    per_query = {}
    if "batch" in extra:
        # the machine rate serves every query of the batch at once:
        # query_gteps = B x value is the delivered query-edge
        # throughput, and 1/query_gteps the per-query ns/edge cost
        # (the ~9/B amortization, PERF_NOTES "query batching");
        # scripts/check_bench.py cross-checks it against batch*value
        qg = round(gteps * extra["batch"], 4)
        # derive the ns cost from the ROUNDED rate so the published
        # pair is self-consistent to the digits it carries
        per_query = {"query_gteps": qg,
                     "per_query_edge_ns": (round(1.0 / qg, 4)
                                           if qg > 0 else None)}
    result = {
        "metric": f"{name}_{unit.lower()}_per_chip",
        "value": round(gteps, 4),
        "unit": unit,
        "vs_baseline": round(gteps / 1.0, 4),
        **per_query,
        "samples": [round(s, 4) for s in samples],
        "attempts": len(samples) if attempts is None else attempts,
        "discarded": [round(d, 4) for d in discarded],
        **({"telemetry": telemetry} if telemetry is not None else {}),
        "calibration": calibration,
        **extra,
    }
    print(json.dumps(result), flush=True)
    return result


def next_artifact_path(directory=".") -> str:
    """BENCH_rNN.json with NN = one past the highest existing round —
    the bench trajectory was EMPTY because artifact assembly was a
    manual step; now the driver metric file writes itself."""
    import os
    import re

    best = 0
    for name in os.listdir(directory or "."):
        m = re.match(r"^BENCH_r(\d+)\.json$", name)
        if m:
            best = max(best, int(m.group(1)))
    return os.path.join(directory or ".", f"BENCH_r{best + 1:02d}.json")


def write_artifact(path, lines, calibration, rc, argv):
    """The machine-readable bench artifact (schema shared with
    scripts/check_bench.py's driver-artifact reader: metric lines
    live in 'tail', one JSON object per line)."""
    doc = {
        "round": None,
        "cmd": "python bench.py " + " ".join(argv),
        "rc": rc,
        "calibration": calibration,
        "tail": "\n".join(json.dumps(ln) for ln in lines),
    }
    import re
    m = re.search(r"BENCH_r(\d+)\.json$", path)
    if m:
        doc["round"] = int(m.group(1))
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"# wrote {path} ({len(lines)} metric line(s))",
          file=sys.stderr)


def config_telemetry(events, start_idx, iter_stats):
    """The metric line's ``telemetry`` field for one config: the
    ``timed_run`` events emitted since ``start_idx`` (one per timed
    repeat, outlier reruns included), the counter digest, and — with
    -health — the watchdog digest from the run's ``health`` event
    (null when the watchdog was off; a TRIPPED watchdog raises and
    the config emits a _FAILED line instead, so a digest here always
    reports a clean bill: tripped=false plus what was checked).
    Round 11 adds ``topology``: null normally, a {shrinks, ndev_final}
    digest when the run's events record a mid-run mesh shrink —
    scripts/check_bench.py REJECTS such lines (a degraded-mesh GTEPS
    must never be compared against full-mesh lines silently).
    scripts/check_bench.py type-checks all four."""
    runs = [{"repeat": ev["repeat"], "iters": ev["iters"],
             "seconds": ev["seconds"]}
            for ev in events.events[start_idx:]
            if ev["kind"] == "timed_run"]
    health = None
    for ev in events.events[start_idx:]:
        if ev["kind"] == "health":
            health = {k: v for k, v in ev.items()
                      if k not in ("t", "tm", "pid", "session",
                                   "kind", "where")}
    shrinks = [ev for ev in events.events[start_idx:]
               if ev["kind"] == "mesh_shrink"]
    topology = None
    if shrinks:
        last = shrinks[-1]
        topology = {"shrinks": len(shrinks),
                    "ndev_final": last.get("to_ndev",
                                           last.get("to_nproc"))}
    # round 13 (lux_tpu/tracing.py era): the per-part imbalance digest
    # — {kind, index (max/mean per-part work), parts (per-part
    # totals)} — null when -iter-stats was off or the engine predates
    # per-part counters.  check_bench cross-validates the index
    # against the parts and the parts sum against the scalar counters.
    return {"runs": runs,
            "counters": (iter_stats.summary()
                         if iter_stats is not None else None),
            "imbalance": (iter_stats.imbalance_digest()
                          if iter_stats is not None else None),
            "health": health,
            "topology": topology}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-config", default=None,
                    choices=list(DEFAULT_SHAPE) + ["batch-sweep"],
                    help="run ONE config (default: all five, "
                         "pagerank last); 'batch-sweep' expands "
                         "ksssp-batch + ppr-batch over -batch "
                         "(one metric line per B)")
    ap.add_argument("-batch", default=BATCH_SWEEP_DEFAULT,
                    help="comma list of query-batch widths B for the "
                         "ksssp-batch/ppr-batch/batch-sweep configs "
                         f"(default {BATCH_SWEEP_DEFAULT!r})")
    ap.add_argument("-all", action="store_true",
                    help="run every config (pagerank last; the "
                         "default when -config is not given)")
    ap.add_argument("-rates", default="15,45",
                    help="comma list of offered qps for the "
                         "serve-slo config (one open-loop load step "
                         "and one metric line per rate)")
    ap.add_argument("-serve-queries", type=int, default=36,
                    dest="serve_queries",
                    help="queries per serve-slo load step")
    ap.add_argument("-serve-batch", type=int, default=4,
                    dest="serve_batch",
                    help="serving engine column count B for "
                         "serve-slo")
    ap.add_argument("-serve-kinds",
                    default="sssp,components,pagerank",
                    dest="serve_kinds",
                    help="mixed query kinds for the serve-slo load")
    ap.add_argument("-serve-replicas", type=int, default=2,
                    dest="serve_replicas",
                    help="replica count for the serve-chaos config "
                         "(lux_tpu/fleet.py; needs >= 2 — one dies)")
    ap.add_argument("-kill-boundary", type=int, default=1,
                    dest="kill_boundary",
                    help="segment boundary (post-warm) of the last "
                         "replica at which the serve-chaos kill plan "
                         "fires")
    ap.add_argument("-slo-ms", dest="slo_ms",
                    default="sssp=250,components=250,pagerank=1000",
                    help="per-kind latency SLO targets for "
                         "serve-slo, kind=ms comma list")
    ap.add_argument("-delta-capacity", type=int, default=64,
                    dest="delta_capacity",
                    help="live-graph delta block capacity for the "
                         "serve-live config (lux_tpu/livegraph.py; "
                         "sized so the mutation stream crosses the "
                         "compact threshold mid-run)")
    ap.add_argument("-reorder", default="none",
                    choices=["none", "native", "hillclimb"],
                    help="page-aware vertex reorder for the "
                         "gather-ab config (lux_tpu/reorder.py): "
                         "'native' = the clustering BFS pass "
                         "(native/reorder.cc), 'hillclimb' = "
                         "candidates + dominant-tile refinement "
                         "scored against the plan's measured "
                         "page_fill.  Non-none expands gather-ab to "
                         "FOUR lines (reordered pair + its paired "
                         "none baseline) so scripts/check_bench.py "
                         "can enforce fill-must-not-decrease")
    ap.add_argument("-shape", default="rmat",
                    choices=["rmat", "community"],
                    help="gather-ab graph family: 'rmat' (the bench "
                         "default — honest negative: little page "
                         "locality to harvest) or 'community' (the "
                         "scrambled planted-partition synthetic, "
                         "convert.community_edges — the locality-"
                         "rich case the reorder pass recovers)")
    ap.add_argument("-scale", type=int, default=0,
                    help="RMAT scale (nv = 2**scale; 0 = per-config "
                         "default)")
    ap.add_argument("-ef", type=int, default=0,
                    help="edges per vertex (0 = per-config default)")
    ap.add_argument("-ni", type=int, default=20,
                    help="iterations (fixed-iteration configs)")
    ap.add_argument("-np", type=int, default=1, help="partitions")
    ap.add_argument("-pair", type=int, default=PAIR_THRESHOLD,
                    help="pair-lane threshold (0 disables)")
    ap.add_argument("-min-fill", type=int, default=-1,
                    dest="min_fill", metavar="F",
                    help="pair rows under F live lanes ride the "
                         "residual instead (ops/pairs.py min_fill; "
                         "measured +33%% on the headline — the "
                         "RMAT21 sweep put the optimum at 24, "
                         "PERF_NOTES round 5; 0 disables; default -1 "
                         "= per-config: 24 for scalar programs, the "
                         "K-AWARE break-even for colfilter's SDDMM "
                         "rows, scalemodel.break_even_fill)")
    ap.add_argument("-repeats", type=int, default=3,
                    help="timed repeats per config; the JSON line "
                         "reports the median (tunnel variance exceeds "
                         "round-over-round gains, PERF_NOTES)")
    ap.add_argument("-retries", type=int, default=2,
                    help="per-config retries for RETRYABLE failures "
                         "(transient worker/tunnel death, classified "
                         "by lux_tpu.resilience); deterministic "
                         "failures (OOM, HTTP 413) never retry")
    ap.add_argument("-backoff", type=float, default=5.0,
                    help="initial retry backoff seconds (doubles per "
                         "retry)")
    ap.add_argument("-outlier", type=float, default=3.0,
                    help="discard-and-rerun factor: samples more than "
                         "F x off the batch median are discarded, "
                         "re-run once, and recorded in 'discarded' "
                         "(VERDICT r5 #7; 0 disables)")
    ap.add_argument("-events", default=None, metavar="FILE",
                    help="append the run's structured telemetry "
                         "events as JSONL to FILE "
                         "(scripts/events_summary.py renders it); "
                         "the per-config 'telemetry' JSON field is "
                         "recorded regardless")
    ap.add_argument("-iter-stats", action="store_true",
                    dest="iter_stats",
                    help="record device-side per-iteration counters "
                         "and put their digest in each line's "
                         "telemetry.counters — runs the engines' "
                         "counter-recording loop variant, so keep it "
                         "OFF for headline numbers (overhead is "
                         "within tunnel noise, PERF_NOTES round 7)")
    ap.add_argument("-health", action="store_true",
                    help="run every config under the device-side "
                         "health watchdog (lux_tpu/health.py) and "
                         "record its digest in telemetry.health — a "
                         "separate compiled loop variant (measured "
                         "within tunnel noise of watchdog-off, "
                         "PERF_NOTES round 9), so keep it OFF for "
                         "headline numbers")
    ap.add_argument("-audit", default="warn",
                    choices=["off", "warn", "error"],
                    help="static program audit of every config's "
                         "engine build (lux_tpu/audit.py; tracing "
                         "only, no extra compiles).  The digest "
                         "lands in each metric line's 'audit' field "
                         "and scripts/check_bench.py REJECTS lines "
                         "from an audit-failing build; 'error' "
                         "additionally fails the config at build "
                         "time, 'off' omits the field")
    ap.add_argument("-json-out", default="auto", dest="json_out",
                    metavar="auto|off|FILE",
                    help="write the machine-readable BENCH artifact "
                         "('auto' = next BENCH_rNN.json in the cwd — "
                         "the hand-assembly gap that left the bench "
                         "trajectory empty; 'off' disables)")
    ap.add_argument("-ledger", default="PERFLEDGER.jsonl",
                    metavar="FILE",
                    help="append every metric line to the persistent "
                         "perf ledger (lux_tpu/observe.py; 'off' "
                         "disables)")
    ap.add_argument("-flight", default=None, metavar="FILE",
                    help="install the crash flight recorder "
                         "(lux_tpu/tracing.py): the resilience "
                         "supervisor dumps the recent-event ring + "
                         "last health word to FILE on fatal/topology "
                         "failures, so a config that dies through "
                         "the tunnel stays diagnosable")
    ap.add_argument("-verbose", action="store_true")
    args = ap.parse_args()
    if args.flight:
        from lux_tpu import tracing
        tracing.install_flight_recorder(args.flight)
    if args.repeats < 1:
        ap.error("-repeats must be >= 1")
    if args.min_fill < -1:
        ap.error("-min-fill must be >= -1 "
                 "(-1 = per-config default, 0 = off)")
    if args.min_fill == -1:      # per-config defaults
        args.min_fill = 24              # scalar rows, round-5 optimum
        args.min_fill_dot = "auto"      # K-aware SDDMM break-even
    elif args.min_fill == 0:
        args.min_fill = args.min_fill_dot = None
    else:
        args.min_fill_dot = args.min_fill

    from lux_tpu import observe, resilience, telemetry

    configs = ([args.config] if args.config and not args.all
               else ["cc", "sssp", "sssp-delta", "colfilter",
                     "sssp-mp", "pagerank-mp", "pagerank"])
    try:
        batch_widths = [int(b) for b in
                        str(args.batch).split(",") if b.strip()]
    except ValueError:
        ap.error(f"-batch must be a comma list of ints, got "
                 f"{args.batch!r}")
    if any(b < 1 for b in batch_widths) or not batch_widths:
        ap.error("-batch widths must be >= 1")
    # expand the batch configs into one sweep point per width
    expanded = []
    for c in configs:
        if c == "batch-sweep":
            expanded += [f"ksssp-batch@{b}" for b in batch_widths]
            expanded += [f"ppr-batch@{b}" for b in batch_widths]
        elif c in ("ksssp-batch", "ppr-batch"):
            expanded += [f"{c}@{b}" for b in batch_widths]
        elif c in ("serve-slo", "serve-chaos"):
            try:
                rates = [float(r) for r in args.rates.split(",")
                         if r.strip()]
            except ValueError:
                ap.error(f"-rates must be a comma list of numbers, "
                         f"got {args.rates!r}")
            if not rates or any(r <= 0 for r in rates):
                ap.error("-rates must be positive offered qps")
            expanded += [f"{c}@{r:g}" for r in rates]
        elif c == "mxu-ab":
            # mxu first (the headline of the A/B); the vpu side is
            # its paired baseline — check_bench rejects an mxu line
            # that arrives without the pair in the same artifact
            expanded += ["mxu-ab@mxu", "mxu-ab@vpu"]
        elif c == "gather-ab":
            # one line per side, paged first (the headline of the
            # A/B); both carry the plan's page stats.  A reorder run
            # ALSO emits the none-reorder pair, so every reordered
            # line has its paired baseline in the same artifact
            # (check_bench enforces fill-must-not-decrease on pairs)
            expanded += ["gather-ab@paged", "gather-ab@flat"]
            if args.reorder != "none":
                expanded += [f"gather-ab@paged:{args.reorder}",
                             f"gather-ab@flat:{args.reorder}"]
        else:
            expanded.append(c)
    configs = expanded
    failures = 0
    # one event log for the whole bench run (in-memory always — the
    # timed_run events are the per-config telemetry field; -events
    # additionally streams them to disk as JSONL)
    events = telemetry.EventLog(args.events)
    # session calibration FIRST (lux_tpu/observe.py): the fixed-cost
    # reference probe stamps every metric line with this process's
    # measured primitive rate vs the canonical figures, so a
    # degraded-tunnel session is labeled at the source.  A probe
    # crash must not take down the bench — the lines then carry
    # calibration=null, which check_bench fails LOUDLY, never
    # silently.
    fingerprint = None
    with telemetry.use(events=events):
        try:
            fingerprint = observe.calibrate()
        except Exception as e:  # noqa: BLE001
            print(f"# calibration probe failed "
                  f"({type(e).__name__}: {e}); metric lines will "
                  f"carry calibration=null", file=sys.stderr)
    cal_digest = None if fingerprint is None else fingerprint.digest()
    if fingerprint is not None and fingerprint.grade == "degraded":
        print(f"# WARNING: DEGRADED session — gather probe "
              f"{fingerprint.deviation:.2f}x off canonical "
              f"(PERF_NOTES tunnel variance); lines are labeled and "
              f"check_bench will reject them from the trajectory",
              file=sys.stderr)
    ledger = (None if args.ledger == "off"
              else observe.PerfLedger(args.ledger))
    metric_lines = []
    for config in configs:
        report = resilience.RunReport()
        policy = resilience.RetryPolicy(retries=max(0, args.retries),
                                        backoff_s=args.backoff)
        st = telemetry.IterStats() if args.iter_stats else None
        events.emit("config_start", config=config,
                    schema=telemetry.SCHEMA)
        idx0 = len(events.events)
        with telemetry.use(events=events, iter_stats=st):
            try:
                # supervised: a transient worker crash retries the
                # whole config (fresh graph+engine — exactly what a
                # dead worker needs) with backoff; fatal classes
                # surface immediately
                (name, samples, extra, rerun), report = \
                    resilience.supervise(
                        lambda k: run_config(config, args), policy,
                        report)
                try:
                    samples, discarded, attempts = \
                        resilience.screen_outliers(
                            samples, rerun, factor=args.outlier)
                except Exception as e:  # noqa: BLE001 — rerun crashed
                    # a crash during an outlier RERUN must not void
                    # the already-measured batch: screen without the
                    # rerun (the discard still drops the collapse)
                    # and record what happened
                    samples, discarded, attempts = \
                        resilience.screen_outliers(
                            samples, None, factor=args.outlier)
                    extra = dict(
                        extra,
                        rerun_error=f"{type(e).__name__}: {e}"[:200],
                        rerun_error_class=resilience.classify(e))
            except Exception as e:  # noqa: BLE001 — one config's crash
                # (e.g. a TPU-worker restart, PERF_NOTES round-5
                # duration wall) must not take down the remaining
                # configs or the tail-line headline metric the driver
                # records
                failures += 1
                failed = {"metric": f"{config}_FAILED",
                          "error": f"{type(e).__name__}: {e}"[:300],
                          "attempts": report.attempts,
                          "failure_class": resilience.classify(e)}
                print(json.dumps(failed), flush=True)
                metric_lines.append(failed)
                continue
        if report.attempts > 1:
            extra = dict(extra, run_attempts=report.attempts)
        line = emit(name, samples, extra, attempts=attempts,
                    discarded=discarded,
                    telemetry=config_telemetry(events, idx0, st),
                    calibration=cal_digest)
        metric_lines.append(line)
        if ledger is not None and fingerprint is not None:
            try:
                ledger.append("bench", line, fingerprint)
            except OSError as e:
                print(f"# perf-ledger append failed: {e}",
                      file=sys.stderr)
    events.close()
    rc = 1 if failures == len(configs) else 0
    if args.json_out != "off" and metric_lines:
        grade = (cal_digest or {}).get("grade")
        if args.json_out == "auto" and grade != "canonical":
            # the BENCH_rNN series IS the trajectory: an auto-minted
            # artifact from a CPU smoke run or a degraded tunnel
            # session would enter it (and trip the repo artifact
            # audit).  The ledger keeps the labeled lines; an
            # explicit -json-out FILE still writes anywhere.
            print(f"# artifact suppressed (session grade="
                  f"{grade}); lines are in the ledger only — pass "
                  f"-json-out FILE to force a file", file=sys.stderr)
        else:
            path = (next_artifact_path() if args.json_out == "auto"
                    else args.json_out)
            try:
                write_artifact(path, metric_lines, cal_digest, rc,
                               sys.argv[1:])
            except OSError as e:
                print(f"# artifact write failed: {e}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
