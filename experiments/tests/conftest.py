import os
import sys

# Make `pytest experiments/tests` work from anywhere: the experiment
# modules import as `experiments.router`, which needs the repo root on
# sys.path (python -m pytest adds it; bare pytest does not).
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
