"""3-stage router planner vs brute-force oracle."""

import numpy as np
import pytest

from experiments.router import W, reduce_numpy
from experiments.router3 import build_route3_plan, route3_numpy


def oracle(src_slot, dst_local, state, vpad):
    out = np.zeros(vpad)
    for s, d in zip(src_slot, dst_local):
        out[d] += state[s]
    return out


def run_case(src_slot, dst_local, vpad, n_state_rows, seed=0):
    plan = build_route3_plan(np.asarray(src_slot),
                             np.asarray(dst_local), vpad, n_state_rows)
    rng = np.random.default_rng(seed)
    state = rng.random(n_state_rows * W)
    state_ext = np.concatenate([state, np.zeros(W)])
    vals = route3_numpy(plan, state_ext)
    got = reduce_numpy(plan, vals, "sum")[plan.out.inv_perm]
    want = oracle(src_slot, dst_local, state, vpad)
    np.testing.assert_allclose(got, want, rtol=1e-9)
    return plan


def test_identity_chain():
    vpad = 2 * W
    run_case(np.arange(vpad), np.arange(vpad), vpad, 3)


def test_random():
    rng = np.random.default_rng(1)
    vpad = 4 * W
    src = rng.integers(0, 8 * W, 5000)
    dst = rng.integers(0, vpad, 5000)
    plan = run_case(src, dst, vpad, 9, seed=2)
    assert plan.stats["gather_per_edge"] < 0.2


def test_skewed():
    rng = np.random.default_rng(3)
    vpad = 8 * W
    src = (rng.zipf(1.3, 20000) - 1) % (16 * W)
    dst = (rng.zipf(1.2, 20000) - 1) % vpad
    run_case(src, dst, vpad, 17, seed=4)


def test_multi_edge_hub():
    src = np.array([5, 5, 5, 300, 300, 7])
    dst = np.array([0, 0, 1, 0, 1, 1])
    run_case(src, dst, 2 * W, 4, seed=5)


def test_exact_delivery():
    rng = np.random.default_rng(6)
    vpad = 4 * W
    ne = 3000
    src = rng.integers(0, 6 * W, ne)
    dst = rng.integers(0, vpad, ne)
    plan = build_route3_plan(src, dst, vpad, 7)
    state = np.arange(7 * W, dtype=np.float64)
    state_ext = np.concatenate([state, np.full(W, -1.0)])
    vals = route3_numpy(plan, state_ext).reshape(-1)
    pos = plan.out.edge_pos
    np.testing.assert_array_equal(vals[pos], src.astype(np.float64))
    pr, pl = np.nonzero(plan.out.need < 0)
    assert (vals[pr * W + pl] == -1.0).all()
