"""Router planner correctness: the numpy execution of the planned
network must reproduce a brute-force segment reduction for arbitrary
graphs (random, skewed, multi-edge, empty-vertex)."""

from __future__ import annotations

import numpy as np
import pytest

from experiments.router import (W, build_route_plan, reduce_numpy,
                                route_numpy)


def oracle(src_slot, dst_local, state, vpad, kind="sum"):
    out = {"sum": np.zeros(vpad),
           "min": np.full(vpad, np.inf),
           "max": np.full(vpad, -np.inf)}[kind]
    op = {"sum": np.add, "min": np.minimum, "max": np.maximum}[kind]
    for s, d in zip(src_slot, dst_local):
        out[d] = op(out[d], state[s])
    return out


def run_case(src_slot, dst_local, vpad, n_state_rows, seed=0, kind="sum"):
    plan = build_route_plan(np.asarray(src_slot), np.asarray(dst_local),
                            vpad, n_state_rows)
    rng = np.random.default_rng(seed)
    state = rng.random(n_state_rows * W)
    ident = {"sum": 0.0, "min": np.inf, "max": -np.inf}[kind]
    state_ext = np.concatenate([state, np.full(W, ident)])

    vals = route_numpy(plan, state_ext)
    got_perm = reduce_numpy(plan, vals, kind)
    # permuted -> original local order
    got = got_perm[plan.out.inv_perm]

    want = oracle(src_slot, dst_local, state, vpad, kind)
    if kind == "sum":
        np.testing.assert_allclose(got, want, rtol=1e-9)
    else:
        # unmasked: inf==inf passes, and a value wrongly leaked into an
        # edge-less vertex's slots fails loudly
        np.testing.assert_allclose(got, want)
    return plan


def test_tiny_identity():
    # one edge per vertex, src = dst slot
    vpad = 2 * W
    src = np.arange(vpad)
    dst = np.arange(vpad)
    run_case(src, dst, vpad, n_state_rows=3)


def test_random_graph():
    rng = np.random.default_rng(1)
    vpad = 4 * W
    ne = 5000
    n_state_rows = 9          # state bigger than vpad (multi-part style)
    src = rng.integers(0, (n_state_rows - 1) * W, ne)
    dst = rng.integers(0, vpad, ne)
    plan = run_case(src, dst, vpad, n_state_rows, seed=2)
    assert plan.stats["ne"] == ne


def test_skewed_hub_graph():
    rng = np.random.default_rng(3)
    vpad = 8 * W
    n_state_rows = 9
    # zipf-ish: most edges to/from a few hubs
    src = (rng.zipf(1.3, 20000) - 1) % ((n_state_rows - 1) * W)
    dst = (rng.zipf(1.2, 20000) - 1) % vpad
    run_case(src, dst, vpad, n_state_rows, seed=4)


def test_multi_edges_and_empty_vertices():
    vpad = 2 * W
    src = np.array([5, 5, 5, 7, 7, 300])
    dst = np.array([0, 0, 0, 0, 1, 1])
    run_case(src, dst, vpad, n_state_rows=4, seed=5)


@pytest.mark.parametrize("kind", ["min", "max"])
def test_min_max_reduce(kind):
    rng = np.random.default_rng(6)
    vpad = 4 * W
    src = rng.integers(0, 3 * W, 3000)
    dst = rng.integers(0, vpad, 3000)
    run_case(src, dst, vpad, n_state_rows=4, seed=7, kind=kind)


def test_single_vertex_mega_hub():
    # one dst receives edges from everywhere (deep tile)
    rng = np.random.default_rng(8)
    vpad = 2 * W
    n_state_rows = 17
    src = rng.integers(0, (n_state_rows - 1) * W, 4000)
    dst = np.zeros(4000, dtype=np.int64)
    dst[:100] = rng.integers(0, vpad, 100)
    run_case(src, dst, vpad, n_state_rows, seed=9)


def test_every_edge_routed_exactly_once():
    rng = np.random.default_rng(10)
    vpad = 4 * W
    ne = 2000
    n_state_rows = 5
    src = rng.integers(0, 4 * W, ne)
    dst = rng.integers(0, vpad, ne)
    plan = build_route_plan(src, dst, vpad, n_state_rows)
    # identify each edge uniquely through the network
    state = np.arange(n_state_rows * W, dtype=np.float64)
    state_ext = np.concatenate([state, np.full(W, -1.0)])
    vals = route_numpy(plan, state_ext).reshape(-1)
    pos = plan.out.edge_pos
    assert len(np.unique(pos)) == ne          # distinct slots
    np.testing.assert_array_equal(vals[pos], src.astype(np.float64))
    # non-edge slots must never contribute real values to the reduce:
    # they either hold the identity (-1 marker here) or sit at garbage
    # window cells... padding output slots specifically must be -1
    pad_rows, pad_lanes = np.nonzero(plan.out.need < 0)
    flat = pad_rows * W + pad_lanes
    assert (vals[flat] == -1.0).all()
