"""Host-side planner for the window-routed pull engine.

The per-edge ``state[src]`` HBM gather costs ~9 ns/edge on TPU v5e and
is 90% of PageRank iteration time (PERF_NOTES.md).  The fast dynamic
primitives are 128-lane shuffles (~0.38 ns/elem), 128x128 block
transposes (~0.35 ns/elem) and static row gathers (~0.19 ns/elem).
This planner wires as many edges as possible through those primitives
and sends only the irreducibly-scattered remainder to the XLA gather.

This replaces the reference's CUB cache-modified per-edge loads
(reference pagerank_gpu.cu:49-102, sssp_gpu.cu:55-56) with routing
fixed at graph-load time — the TPU-native equivalent of building the
CSC in framebuffer memory once and letting threads chase pointers.

Output layout (slotted-positional)
----------------------------------
Vertices of a part are in-degree-sorted (permuted); tile = 128
consecutive permuted vertices; output row = (tile, edge rank); lane =
vertex % 128.  Tiles have uniform-ish depth after the degree sort and
are grouped into depth classes, so the segment reduction is a static
``reshape(T, L, 128).sum(axis=1)`` per class — no scan, no compare,
no scatter (1.3-1.6x slot inflation on power-law graphs).

Delivery network
----------------
Output rows are processed in blocks of 128 rows.  A block's 16K source
needs are assigned *stage positions* k in [0, 128): the z-array holds
``z[(b, k), i]`` = the k-th staged value of the block's i-th output
row; ``zT = block-transpose(z)`` then puts each output row's staged
values in one row, and one lane shuffle (sigma3) delivers them to
edge slots.  Positions are filled two ways:

- *window* (pure) positions: a contiguous window of positions is bound
  to one state2d row r; ``z[(b,k), :] = shuffle(state2d[r])``.  Cells
  not needed by some output row hold garbage — harmless, sigma3 never
  selects them.  Windows are allocated greedily to the block's
  highest-demand state rows (hubs first, thanks to the degree sort).
- *spill* positions: filled by one compact XLA gather
  ``take(state, spill_need)`` — only actually-needed values plus the
  identity cell each block keeps at its last position for padding
  output slots.
"""

from __future__ import annotations

import dataclasses

import numpy as np

W = 128


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def occurrence_index(vals) -> np.ndarray:
    """Occurrence index of each element among equal values (0 for the
    first occurrence, 1 for the second, ...)."""
    srt = np.argsort(vals, kind="stable")
    vs = np.asarray(vals)[srt]
    newg = np.ones(len(vs), bool)
    newg[1:] = vs[1:] != vs[:-1]
    pos = np.arange(len(vs))
    gst = np.maximum.accumulate(np.where(newg, pos, 0))
    occ = np.empty(len(vs), np.int64)
    occ[srt] = pos - gst
    return occ


# ---------------------------------------------------------------------------
# slotted output layout
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SlottedOut:
    """Slotted-positional output rows for one part (permuted dst space)."""

    perm: np.ndarray          # int32 [vpad]: perm[new_local] = old_local
    inv_perm: np.ndarray      # int32 [vpad]: inv_perm[old_local] = new_local
    n_tiles: int
    tile_depth: np.ndarray    # int32 [n_tiles] rows per tile (level-padded)
    need: np.ndarray          # int64 [R_out, 128] global state slot, -1 pad
    edge_pos: np.ndarray      # int64 [ne] slot (row*128+lane) per input edge
    classes: list             # [(tile_start, tile_count, depth)]
    R_out: int

    @classmethod
    def build(cls, src_slot: np.ndarray, dst_local: np.ndarray,
              vpad: int, levels_growth: float = 1.35) -> "SlottedOut":
        assert vpad % W == 0
        ne = len(dst_local)
        indeg = np.bincount(dst_local, minlength=vpad).astype(np.int64)
        order = np.argsort(-indeg, kind="stable")
        perm = order.astype(np.int32)
        inv_perm = np.empty(vpad, np.int32)
        inv_perm[order] = np.arange(vpad, dtype=np.int32)

        n_tiles = vpad // W
        d_sorted = indeg[order]
        raw_depth = np.maximum(d_sorted.reshape(n_tiles, W).max(axis=1), 1)

        levels = [1, 2, 3, 4, 5, 6, 7, 8]
        v = 8
        while v < int(raw_depth.max()):
            v = int(v * levels_growth) + 1
            levels.append(v)
        lev = np.asarray(levels, dtype=np.int64)
        depth = lev[np.searchsorted(lev, raw_depth)]
        assert (np.diff(depth) <= 0).all()   # tiles depth-sorted

        row_off = np.concatenate(([0], np.cumsum(depth)))
        R_real = int(row_off[-1])
        R_out = _ceil_to(R_real, W)

        need = np.full((R_out, W), -1, dtype=np.int64)
        nd = inv_perm[dst_local].astype(np.int64)
        sort_idx = np.argsort(nd, kind="stable")
        nd_s = nd[sort_idx]
        src_s = np.asarray(src_slot, np.int64)[sort_idx]
        starts = np.searchsorted(nd_s, np.arange(vpad))
        rank = np.arange(ne, dtype=np.int64) - starts[nd_s]
        rows = row_off[nd_s // W] + rank
        lanes = nd_s % W
        need[rows, lanes] = src_s
        edge_pos = np.empty(ne, dtype=np.int64)
        edge_pos[sort_idx] = rows * W + lanes

        classes = []
        t0 = 0
        for L in np.unique(depth)[::-1]:
            cnt = int((depth == L).sum())
            classes.append((t0, cnt, int(L)))
            t0 += cnt
        return cls(perm=perm, inv_perm=inv_perm, n_tiles=n_tiles,
                   tile_depth=depth.astype(np.int32), need=need,
                   edge_pos=edge_pos, classes=classes, R_out=R_out)


# ---------------------------------------------------------------------------
# window routing plan
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RoutePlan:
    """Static routing arrays for one part.

    Device pipeline (route_exec.py):
        zdir  = shuffle(state2d[rowbind], sigma_z)     # [Zd, 128]
        zsp   = take(state_ext, spill_need)            # [Zs, 128]
        z     = concat(zdir, zsp)[zorder]              # [nb*128, 128]
        zT    = block-transpose(z)                     # rows = out rows
        vals  = shuffle(zT, sigma3)                    # [R_out, 128]
        out   = per-class reshape-reduce -> [vpad] (permuted)
    """

    rowbind: np.ndarray       # int32 [Zd] state2d row per direct z-row
    sigma_z: np.ndarray       # int32 [Zd, 128]
    spill_need: np.ndarray    # int32 [Zs, 128] flat state slot (or dead)
    zorder: np.ndarray        # int32 [nb*128] -> row in concat(zdir, zsp)
    sigma3: np.ndarray        # int32 [R_out, 128]
    n_blocks: int
    out: SlottedOut
    dead_slot: int            # flat index of the identity cell in
                              # state_ext (== vpad; state_ext has one
                              # extra 128-wide identity row)
    stats: dict


def build_route_plan(src_slot: np.ndarray, dst_local: np.ndarray,
                     vpad: int, n_state_rows: int) -> RoutePlan:
    """Plan delivery for one part.

    src_slot: int [ne] global padded state slot of each edge's source
              (into the un-extended state vector of n_state_rows*128).
    dst_local: int [ne] part-local dst in [0, vpad).

    The device must run the network against ``state_ext`` = flat state
    with one extra identity row appended (plan.dead_slot points into
    that row).
    """
    out = SlottedOut.build(src_slot, dst_local, vpad)
    R = out.R_out
    nb = R // W
    dead_slot = n_state_rows * W
    if dead_slot >= 2**31:
        raise ValueError(
            f"state slot space {dead_slot} overflows the int32 routing "
            f"indices; shard into more parts")

    need = out.need                          # [R, 128], -1 = padding

    rowbind_l: list[np.ndarray] = []
    sigma_z_l: list[np.ndarray] = []
    spill_l: list[np.ndarray] = []
    zorder = np.empty(nb * W, dtype=np.int64)
    sigma3 = np.zeros((R, W), dtype=np.int32)

    spill_rows_total = 0
    direct_needs = 0
    live_needs = 0

    for b in range(nb):
        nb_need = need[b * W:(b + 1) * W]            # [128, 128]
        i_idx, j_idx = np.nonzero(nb_need >= 0)
        needs = nb_need[i_idx, j_idx]
        rows_flat = needs // W
        live_needs += len(rows_flat)

        if len(rows_flat):
            # occurrence index within each (output row i, state row r)
            key = i_idx.astype(np.int64) * n_state_rows + rows_flat
            srt = np.argsort(key, kind="stable")
            ks = key[srt]
            grp_new = np.ones(len(ks), bool)
            grp_new[1:] = ks[1:] != ks[:-1]
            pos = np.arange(len(ks))
            gstart = np.maximum.accumulate(np.where(grp_new, pos, 0))
            occ = np.empty(len(ks), np.int64)
            occ[srt] = pos - gstart
            # per-r window demand (max over i) and total demand
            grp_cnt = np.diff(np.concatenate(
                (np.nonzero(grp_new)[0], [len(ks)])))
            grp_r = rows_flat[srt][grp_new]
            uniq_r, r_inv = np.unique(grp_r, return_inverse=True)
            wmax = np.zeros(len(uniq_r), np.int64)
            np.maximum.at(wmax, r_inv, grp_cnt)
            total = np.zeros(len(uniq_r), np.int64)
            np.add.at(total, r_inv, grp_cnt)
        else:
            uniq_r = np.zeros(0, np.int64)
            wmax = total = uniq_r
            occ = np.zeros(0, np.int64)

        # allocate windows by demand density, then shrink until the
        # block fits: n_win + n_spill (+1 if padding needs an extra
        # identity row) <= 128
        dens_order = np.argsort(-(total * 1000) // np.maximum(wmax, 1))
        has_pad = bool((nb_need < 0).any())

        def layout(n_take):
            """windows = first n_take rows of dens_order; returns
            (win_start map arrays, n_win, spill arrays, n_spill)."""
            win_start = np.full(len(uniq_r), -1, np.int64)
            used = 0
            taken = []
            for rj in dens_order[:n_take]:
                if used + wmax[rj] > W - 1:
                    continue
                win_start[rj] = used
                used += int(wmax[rj])
                taken.append(int(rj))
            if len(rows_flat):
                r_pos = np.searchsorted(uniq_r, rows_flat)
                starts_arr = win_start[r_pos]
            else:
                starts_arr = np.zeros(0, np.int64)
            dm = starts_arr >= 0
            sp_i = i_idx[~dm]
            if len(sp_i):
                cnt_i = np.bincount(sp_i, minlength=W)
                n_spill = int(cnt_i.max())
            else:
                cnt_i = np.zeros(W, np.int64)
                n_spill = 0
            n_spill = max(n_spill, 1)
            extra = 1 if (has_pad and
                          (cnt_i[(nb_need < 0).any(axis=1)] >= n_spill
                           ).any()) else 0
            return win_start, used, taken, dm, starts_arr, cnt_i, \
                n_spill + extra

        n_take = len(uniq_r)
        while True:
            (win_start, n_win, taken, dm, starts_arr, cnt_i,
             n_spill) = layout(n_take)
            if n_win + n_spill <= W:
                break
            n_take = max(0, min(n_take - 1, len(taken) - 1))

        direct_needs += int(dm.sum())

        # position table
        posn = np.full((W, W), -1, np.int64)
        if len(rows_flat):
            posn[i_idx[dm], j_idx[dm]] = starts_arr[dm] + occ[dm]
            sp_i, sp_j = i_idx[~dm], j_idx[~dm]
            srt2 = np.argsort(sp_i, kind="stable")
            sp_i, sp_j = sp_i[srt2], sp_j[srt2]
            st = np.searchsorted(sp_i, np.arange(W))
            sp_rank = np.arange(len(sp_i)) - st[sp_i]
            posn[sp_i, sp_j] = n_win + sp_rank
        else:
            sp_i = sp_j = sp_rank = np.zeros(0, np.int64)

        spill = np.full((n_spill, W), dead_slot, np.int64)
        if len(sp_i):
            spill[sp_rank, sp_i] = nb_need[sp_i, sp_j]

        # direct z-rows
        rb = np.repeat(uniq_r[taken].astype(np.int32)
                       if taken else np.zeros(0, np.int32),
                       wmax[taken].astype(np.int64) if taken else [])
        base_dir = sum(len(x) for x in rowbind_l)
        rowbind_l.append(rb)
        sz = np.zeros((n_win, W), np.int32)
        if len(rows_flat):
            sz[(starts_arr[dm] + occ[dm]), i_idx[dm]] = \
                (needs[dm] % W).astype(np.int32)
        sigma_z_l.append(sz)
        spill_l.append(spill)

        # z assembly order: windows, spill, pad (never selected)
        zo = np.empty(W, np.int64)
        zo[:n_win] = base_dir + np.arange(n_win)
        zo[n_win:n_win + n_spill] = -1 - (spill_rows_total
                                          + np.arange(n_spill))
        zo[n_win + n_spill:] = -1 - spill_rows_total
        zorder[b * W:(b + 1) * W] = zo
        spill_rows_total += n_spill

        # sigma3: padding output slots of row i -> spill rank cnt_i[i]
        # (that cell is dead by construction; the layout() pass added
        # an extra all-dead spill row when some padded row used every
        # spill rank)
        pad_here = nb_need < 0
        pad_pos = n_win + cnt_i                       # [W] per out row
        posn = np.where(pad_here, pad_pos[:, None], posn)
        assert (posn >= 0).all() and (posn < W).all()
        sigma3[b * W:(b + 1) * W] = posn.astype(np.int32)

    Zd = sum(len(x) for x in rowbind_l)
    rowbind = (np.concatenate(rowbind_l) if Zd
               else np.zeros(0, np.int32))
    sigma_z = (np.concatenate(sigma_z_l, axis=0) if Zd
               else np.zeros((0, W), np.int32))
    spill_need = (np.concatenate(spill_l, axis=0) if spill_l
                  else np.zeros((0, W), np.int64))
    Zs = spill_need.shape[0]
    zorder = np.where(zorder >= 0, zorder,
                      Zd + (-1 - zorder)).astype(np.int32)

    ne = len(dst_local)
    plan = RoutePlan(
        rowbind=rowbind.astype(np.int32),
        sigma_z=sigma_z.astype(np.int32),
        spill_need=spill_need.astype(np.int32),
        zorder=zorder, sigma3=sigma3, n_blocks=nb, out=out,
        dead_slot=dead_slot, stats={})
    plan.stats = dict(
        ne=ne, R_out=R, n_blocks=nb, Zd=Zd, Zs=Zs,
        direct_needs=direct_needs, live_needs=live_needs,
        direct_frac=direct_needs / max(live_needs, 1),
        spill_slots=Zs * W,
        gather_per_edge=Zs * W / max(ne, 1),
        out_inflation=R * W / max(ne, 1))
    return plan


# ---------------------------------------------------------------------------
# numpy reference executor (oracle for tests)
# ---------------------------------------------------------------------------

def route_numpy(plan: RoutePlan, state_ext: np.ndarray) -> np.ndarray:
    """state_ext: flat state INCLUDING the identity row at
    plan.dead_slot's row.  Returns [R_out, 128] delivered values."""
    s2d = np.asarray(state_ext).reshape(-1, W)
    if plan.rowbind.size:
        zdir = np.take_along_axis(s2d[plan.rowbind], plan.sigma_z, axis=1)
    else:
        zdir = np.zeros((0, W), s2d.dtype)
    zsp = np.asarray(state_ext)[plan.spill_need]
    z = np.concatenate([zdir, zsp], axis=0)[plan.zorder]
    zT = (z.reshape(plan.n_blocks, W, W)
          .transpose(0, 2, 1).reshape(-1, W))
    return np.take_along_axis(zT, plan.sigma3, axis=1)


def reduce_numpy(plan: RoutePlan, vals: np.ndarray, kind="sum"):
    """Per-class positional reduce -> [vpad] in PERMUTED local order."""
    outs = []
    row0 = 0
    op = {"sum": np.add.reduce, "min": np.minimum.reduce,
          "max": np.maximum.reduce}[kind]
    for (_t0, cnt, L) in plan.out.classes:
        rows = vals[row0:row0 + cnt * L].reshape(cnt, L, W)
        outs.append(op(rows, axis=1))
        row0 += cnt * L
    return np.concatenate(outs, axis=0).reshape(-1)
