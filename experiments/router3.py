"""Three-stage shuffle-routing planner ("deep router").

Extends router.py's window planner with the staging depth that the
power-law tail needs (PERF_NOTES.md "Routing-network experiments"):
instead of spilling every value whose z-row spans multiple state rows
to the 9 ns/edge XLA gather, values flow through up to three
shuffle/transpose stages, each a fast primitive (~0.4 ns/elem):

  x-layer   global *band instances*: state rows are grouped into <=128
            contiguous 128-row bands (degree-sorted, so bands are
            contiguous quantiles).  An instance binds <=128 rows of one
            band (with multiplicity); each instance column (an ``xT``
            row after the block transpose) carries up to 128 values of
            that band destined for ONE out-block.
  w-layer   per-out-block *band mixers*: a w-row lane-shuffles one xT
            row; a wT column mixes <=1 value per w-row — i.e. up to 128
            values from up to 128 different bands: full reach.
  z-layer   staged rows feeding the output: z-row (b, k) lane-shuffles
            ONE pool row — a state2d row (direct, pure z-rows), an xT
            row (single-band z-rows), or a wT row (mixed z-rows) —
            placing values into out-row-indexed lanes.
  out       block-transpose + sigma3 shuffle + per-class positional
            reduce (same machinery as router.py).

Anything that still does not fit (capacity overflows) spills to the
compact XLA gather, but unlike the 1-stage planner the spill is a few
percent, not ~95%.

The device pipeline would be three rounds of [row-gather ->
lane-shuffle -> batched 128x128 transpose] plus the spill gather — all
measured-fast primitives.  It is NOT implemented: real-graph planner
stats (PERF_NOTES.md "Deep-router") show the x-layer collapses to ~1%
utilization on power-law tails, so this module stands as the tested
record of that design point; ``route3_numpy`` is the only executor.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from experiments.router import (SlottedOut, W,
                                occurrence_index as _occ)


@dataclasses.dataclass
class Route3Plan:
    """Static arrays for the 3-stage network of one part.

    Pools (rows available to the next layer), in order:
      state2d  [S, 128]     (S includes NO dead row; dead handled via
                             the spill gather and unselected lanes)
      xT       [X, 128]     X = n_xblocks * 128
      wT       [Wn, 128]    Wn = n_wblocks * 128
      spill    [Zs, 128]    gathered rows

    z assembly: z[(b,k), :] = shuffle(pool[zbind[(b,k)]], sigma_z) with
    pool = concat(state2d, xT, wT, spill); zbind indexes that concat.
    """

    # x-layer
    xbind: np.ndarray        # int32 [X] state2d row per x-row
    sigma_x: np.ndarray      # int32 [X, 128]
    n_xblocks: int
    # w-layer
    wbind: np.ndarray        # int32 [Wn] row into concat(state2d, xT)
    sigma_w: np.ndarray      # int32 [Wn, 128]
    n_wblocks: int
    # z-layer
    zbind: np.ndarray        # int32 [Z] row into the full pool
    sigma_z: np.ndarray      # int32 [Z, 128]
    # spill + out
    spill_need: np.ndarray   # int32 [Zs, 128] flat slot into state_ext
    sigma3: np.ndarray       # int32 [R_out, 128]
    n_blocks: int
    out: SlottedOut
    dead_slot: int
    n_state_rows: int
    stats: dict


def build_route3_plan(src_slot: np.ndarray, dst_local: np.ndarray,
                      vpad: int, n_state_rows: int) -> Route3Plan:
    out = SlottedOut.build(src_slot, dst_local, vpad)
    R = out.R_out
    nb = R // W
    S = n_state_rows
    dead_slot = S * W
    if dead_slot >= 2**31:
        raise ValueError("state slot space overflows int32 routing")

    need = out.need                                  # [R,128], -1 pad
    srow = np.where(need >= 0, need // W, -1)

    # ---- z-rows by per-out-row rank sort --------------------------------
    order = np.argsort(np.where(srow < 0, np.int64(1) << 40, srow),
                       axis=1, kind="stable")
    sigma3 = np.empty((R, W), dtype=np.int32)
    np.put_along_axis(
        sigma3, order,
        np.broadcast_to(np.arange(W, dtype=np.int32), (R, W)), axis=1)
    srow_k = np.take_along_axis(srow, order, axis=1)
    scol_k = np.take_along_axis((need % W).astype(np.int32), order,
                                axis=1)
    Z = nb * W
    srow_z = (srow_k.reshape(nb, W, W).transpose(0, 2, 1).reshape(Z, W))
    scol_z = (scol_k.reshape(nb, W, W).transpose(0, 2, 1).reshape(Z, W))
    live = srow_z >= 0

    # bands: contiguous 128-row groups of state rows
    n_bands = (S + W - 1) // W
    band_z = np.where(live, srow_z // W, -1)

    # ---- classify z-rows ------------------------------------------------
    any_live = live.any(axis=1)
    first = np.where(any_live, live.argmax(axis=1), 0)
    ref_row = srow_z[np.arange(Z), first]
    ref_band = band_z[np.arange(Z), first]
    pure_row = ((np.where(live, srow_z, ref_row[:, None])
                 == ref_row[:, None]).all(axis=1) & any_live)
    # single-band: all live values in one band, and within the band no
    # state row needed twice... multiplicity IS allowed via instance
    # multiplicity, but a single xT row holds <=1 value per x-row; we
    # bind x-instances with multiplicity, so duplicates are fine as
    # long as the (block, band) column capacity (128) holds.
    one_band = ((np.where(live, band_z, ref_band[:, None])
                 == ref_band[:, None]).all(axis=1) & any_live)

    kind = np.full(Z, 2, np.int8)        # 2 = mixed (w-layer)
    kind[one_band] = 1                   # 1 = single-band (xT direct)
    kind[pure_row] = 0                   # 0 = direct state row
    kind[~any_live] = 3                  # 3 = all-dead (sigma3-proof)

    # ---- x-layer construction ------------------------------------------
    # Demands: for kind-1 z-rows: one xT row carrying ALL its values
    # (columns of an instance of its band).  For kind-2 z-rows: per
    # band, the block's w-layer needs xT rows carrying the block's
    # values of that band.  Group kind-2 demands by (out-block, band).
    #
    # An x-instance of band beta has 128 columns; each column is an xT
    # row: EITHER a kind-1 z-row's full value set, OR a (block, band,
    # copy) value set for the w-layer.  Column constraint: <=1 value
    # per x-row; instance binds band rows with multiplicity = max over
    # its columns' per-row counts (<=128 total).

    x_cols: dict[int, list] = {b: [] for b in range(n_bands)}
    # each entry: (tag, payload); tag "z1" payload = z-row id;
    # tag "w" payload = (block, band, rows[], cols[], zk[], zl[])

    for zi in np.nonzero(kind == 1)[0]:
        x_cols[int(ref_band[zi])].append(("z1", int(zi)))

    # kind-2 z-rows: first partition them into W-GROUPS.  A w-group is
    # a future w-block: its w-rows are (band, copy) slots, its columns
    # are member z-rows.  Budget per group: sum over bands of the max
    # per-member per-band value count <= 128 w-rows, and <= 128
    # members (columns).  Hub-heavy blocks overflow a single group,
    # so out-blocks may own several.
    mixed = np.nonzero(kind == 2)[0]
    wgroup_of = np.full(Z, -1, np.int64)      # z-row -> w-group id
    wcol_of = np.full(Z, -1, np.int64)        # z-row -> column in group
    n_wgroups = 0
    if mixed.size:
        # per-z-row per-band counts (sparse: bands + counts per row)
        zrow_bands = []
        for zi in mixed:
            bz = band_z[zi][live[zi]]
            ub, uc = np.unique(bz, return_counts=True)
            zrow_bands.append((ub, uc))
        cur_counts: dict[int, int] = {}
        cur_members = 0
        for idx, zi in enumerate(mixed):
            ub, uc = zrow_bands[idx]
            grow = sum(max(0, int(c) - cur_counts.get(int(bb), 0))
                       for bb, c in zip(ub, uc))
            if (cur_members >= W or
                    sum(cur_counts.values()) + grow > W) \
                    and cur_members > 0:
                n_wgroups += 1
                cur_counts = {}
                cur_members = 0
            for bb, c in zip(ub, uc):
                cur_counts[int(bb)] = max(cur_counts.get(int(bb), 0),
                                          int(c))
            assert sum(cur_counts.values()) <= W, \
                "mixed z-row alone exceeds w capacity"
            wgroup_of[zi] = n_wgroups
            wcol_of[zi] = cur_members
            cur_members += 1
        if cur_members:
            n_wgroups += 1

        # per (w-group, band): values of member z-rows, split into
        # copies (a wT column takes <=1 value per w-row, so a z-row
        # with m values from one band needs m copies of that band).
        mz = mixed.repeat(W)
        lanes = np.tile(np.arange(W), mixed.size)
        lv = live[mz, lanes]
        mz, lanes = mz[lv], lanes[lv]
        groups_of = wgroup_of[mz]
        bands_of = band_z[mz, lanes]
        key = groups_of * n_bands + bands_of
        srt = np.argsort(key, kind="stable")
        mz, lanes, key = mz[srt], lanes[srt], key[srt]
        grp_starts = np.concatenate(
            ([0], np.nonzero(key[1:] != key[:-1])[0] + 1, [len(key)]))
        for gi in range(len(grp_starts) - 1):
            lo, hi = grp_starts[gi], grp_starts[gi + 1]
            wg = int(wgroup_of[mz[lo]])
            beta = int(band_z[mz[lo], lanes[lo]])
            zids = mz[lo:hi]
            lns = lanes[lo:hi]
            occ = _occ(zids)
            n_copies = int(occ.max()) + 1
            for cp in range(n_copies):
                sel = occ == cp
                x_cols[beta].append(
                    ("w", (wg, beta, cp,
                           srow_z[zids[sel], lns[sel]],
                           scol_z[zids[sel], lns[sel]],
                           zids[sel], lns[sel])))

    # pack columns into instances per band (capacity: 128 columns and
    # sum of row multiplicities <= 128)
    xbind_l: list[np.ndarray] = []
    sigma_x_l: list[np.ndarray] = []
    xT_of: dict = {}          # ("z1", zi) or ("w", b, beta, copy#) ->
                              # global xT row, plus per-value slots
    x_slot_of: dict = {}      # same key -> {(row,col,occ): slot}

    n_xblocks = 0
    for beta, cols in x_cols.items():
        ci = 0
        while ci < len(cols):
            # greedy: take columns while capacity holds
            inst_cols = []
            mult: dict[int, int] = {}
            while ci < len(cols) and len(inst_cols) < W:
                tag, payload = cols[ci]
                if tag == "z1":
                    zi = payload
                    lvz = live[zi]
                    rows_i, counts_i = np.unique(srow_z[zi][lvz],
                                                 return_counts=True)
                else:
                    (_b, _beta, _cp, vrows, vcols, vzk, vzl) = payload
                    rows_i, counts_i = np.unique(vrows,
                                                 return_counts=True)
                m2 = dict(mult)
                for r, c in zip(rows_i, counts_i):
                    m2[int(r)] = max(m2.get(int(r), 0), int(c))
                if sum(m2.values()) > W and inst_cols:
                    break
                if sum(m2.values()) > W:
                    raise AssertionError("x column alone exceeds 128")
                mult = m2
                inst_cols.append(cols[ci])
                ci += 1
            # emit instance
            k_of: dict[int, int] = {}
            k = 0
            rb = np.zeros(W, np.int32)
            for r, m in mult.items():
                k_of[r] = k
                rb[k:k + m] = r
                k += m
            rb[k:] = rb[0] if k else 0
            sx = np.zeros((W, W), np.int32)
            for col_idx, (tag, payload) in enumerate(inst_cols):
                if tag == "z1":
                    zi = payload
                    lvz = live[zi]
                    vrows = srow_z[zi][lvz]
                    vcols = scol_z[zi][lvz]
                    key2 = ("z1", zi)
                else:
                    (pb, pbeta, pcp, vrows, vcols, vzk, vzl) = payload
                    key2 = ("w", pb, pbeta, pcp)
                # occurrence per row within this column
                o = _occ(vrows)
                slots = np.array([k_of[int(r)] for r in vrows],
                                 np.int64) + o
                sx[slots, col_idx] = vcols
                xT_of[key2] = n_xblocks * W + col_idx
                x_slot_of[key2] = slots
            xbind_l.append(rb)
            sigma_x_l.append(sx)
            n_xblocks += 1

    xbind = (np.concatenate(xbind_l) if xbind_l
             else np.zeros(0, np.int32))
    sigma_x = (np.concatenate(sigma_x_l, axis=0) if sigma_x_l
               else np.zeros((0, W), np.int32))

    # ---- w-layer: one block per out-block that has mixed z-rows --------
    wbind_l: list[np.ndarray] = []
    sigma_w_l: list[np.ndarray] = []
    n_wblocks = 0
    # z assembly
    zbind = np.zeros(Z, np.int64)
    sigma_z = np.zeros((Z, W), np.int32)
    spill_rows: list[np.ndarray] = []

    X = n_xblocks * W
    pool_x0 = S                    # xT rows start here in pool indexing
    pool_w0 = S + X

    # direct z-rows
    for zi in np.nonzero(kind == 0)[0]:
        zbind[zi] = ref_row[zi]
        sigma_z[zi] = np.where(live[zi], scol_z[zi], 0)
    # (kind-3 all-dead rows are bound to the spill identity row after
    # the spill layer is laid out below)

    # single-band z-rows: z = shuffle of their xT row; the xT row holds
    # the values at slots x_slot_of -> sigma_z maps out-lane -> slot
    for zi in np.nonzero(kind == 1)[0]:
        key2 = ("z1", int(zi))
        xt = xT_of[key2]
        slots = x_slot_of[key2]
        lanes_live = np.nonzero(live[zi])[0]
        zbind[zi] = pool_x0 + xt
        sz = np.zeros(W, np.int32)
        sz[lanes_live] = slots
        sigma_z[zi] = sz
    # (dead lanes of kind 0/1/3 z-rows carry garbage; sigma3 never
    #  selects them — padding out-slots are pointed at spill identity
    #  cells below.)

    # mixed z-rows: per out-block build the w-block
    # regroup the "w" columns by out-block
    wcols_by_group: dict[int, list] = {}
    payload_of = {}
    for beta, cols in x_cols.items():
        for tag, payload in cols:
            if tag == "w":
                key2 = ("w", payload[0], payload[1], payload[2])
                payload_of[key2] = payload
                wcols_by_group.setdefault(payload[0], []).append(key2)

    for wg, keys2 in sorted(wcols_by_group.items()):
        assert len(keys2) <= W, "w-group band-copy budget violated"
        wb = np.zeros(W, np.int32)
        sw = np.zeros((W, W), np.int32)
        for m, key2 in enumerate(keys2):
            (_pg, _pbeta, _pcp, vrows, vcols, vzk, vzl) = \
                payload_of[key2]
            wb[m] = pool_x0 + xT_of[key2]
            slots = x_slot_of[key2]
            # w[m, c]: lane c = the z-row's column within its w-group;
            # the wT column c holds z-row c's values, one per (band,
            # copy) row m; sigma_z routes out-lane -> m.
            sw[m, wcol_of[vzk]] = slots
            sigma_z[vzk, vzl] = m
        wbind_l.append(wb)
        sigma_w_l.append(sw)
    n_wblocks = n_wgroups
    mixed_all = np.nonzero(kind == 2)[0]
    zbind[mixed_all] = (pool_w0 + wgroup_of[mixed_all] * W +
                        wcol_of[mixed_all])

    wbind = (np.concatenate(wbind_l) if wbind_l
             else np.zeros(0, np.int32))
    sigma_w = (np.concatenate(sigma_w_l, axis=0) if sigma_w_l
               else np.zeros((0, W), np.int32))

    # ---- spill layer: identity cells for padding output slots ----------
    # one spill row per out-block that has padding slots; cell [0, i]
    # = dead for all i.
    Wn = n_wblocks * W
    pool_s0 = S + X + Wn
    spill_need = np.full((1, W), dead_slot, np.int64)   # shared row
    # Padding out-slots must read the identity; the resolution: point
    # them (via sigma3) at a z position whose cell is identity for
    # every lane — an all-dead (kind-3) z-row bound to the spill
    # identity row, or, for blocks without one, position 127 converted
    # into a spill-backed row (its dead lanes gather the identity).
    for zi in np.nonzero(kind == 3)[0]:
        zbind[zi] = pool_s0 + 0
        sigma_z[zi] = 0
    # padding out-slots: their rank-k positions: if that z-row is
    # kind 3 -> identity (ok).  If the z-row has live lanes (mixed
    # dead/live), lane i is dead there by construction (out-row i's
    # k-th rank is dead only when ranks >= its live count; z-row k has
    # i's k-th ranked need...).  For such rows we must deliver
    # identity at lane i: only kind-2 rows can mix sources per lane?
    # No -- every z-row has ONE source row.  Fix: route padding slots
    # through a dedicated spill z position is impossible (depth 128).
    # Instead re-point sigma3 for padding slots at position k* where
    # k* is a kind-3 z-row of the block (exists iff some out-row in
    # the block is fully padded...).  Not guaranteed.  FALLBACK: for
    # blocks with padding but no kind-3 row, convert their LAST z-row
    # (k=127, the most-dead position) to a spill row gathering its
    # live needs + identity elsewhere.
    sp_count = 1
    for b in range(nb):
        blk = slice(b * W, (b + 1) * W)
        needb = need[blk]
        if not (needb < 0).any():
            continue
        zk3 = np.nonzero(kind[b * W:(b + 1) * W] == 3)[0]
        if zk3.size:
            kstar = int(zk3[0])
        else:
            # convert position 127 into a spill row
            zi = b * W + (W - 1)
            row = np.full(W, dead_slot, np.int64)
            lvz = live[zi]
            row[np.nonzero(lvz)[0]] = (srow_z[zi][lvz].astype(np.int64)
                                       * W + scol_z[zi][lvz])
            spill_rows.append(row)
            zbind[zi] = pool_s0 + sp_count
            sigma_z[zi] = np.arange(W, dtype=np.int32)
            kind[zi] = 4                      # spill-backed
            sp_count += 1
            kstar = W - 1
        pr, pl = np.nonzero(needb < 0)
        sigma3[b * W + pr, pl] = kstar

    if spill_rows:
        spill_need = np.concatenate(
            [spill_need, np.stack(spill_rows)], axis=0)
    Zs = spill_need.shape[0]

    live_vals = int(live.sum())
    plan = Route3Plan(
        xbind=xbind, sigma_x=sigma_x, n_xblocks=n_xblocks,
        wbind=wbind, sigma_w=sigma_w, n_wblocks=n_wblocks,
        zbind=zbind.astype(np.int32), sigma_z=sigma_z,
        spill_need=spill_need.astype(np.int32), sigma3=sigma3,
        n_blocks=nb, out=out, dead_slot=dead_slot, n_state_rows=S,
        stats={})
    ne = len(dst_local)
    plan.stats = dict(
        ne=ne, R_out=R, Z=Z, X=X, Wn=Wn, Zs=Zs,
        n_xblocks=n_xblocks, n_wblocks=n_wblocks,
        kinds={int(kk): int((kind == kk).sum()) for kk in range(5)},
        gather_per_edge=Zs * W / max(ne, 1),
        x_slots_per_edge=X * W / max(ne, 1),
        w_slots_per_edge=Wn * W / max(ne, 1),
        out_inflation=R * W / max(ne, 1))
    return plan


# ---------------------------------------------------------------------------
# numpy reference executor
# ---------------------------------------------------------------------------

def route3_numpy(plan: Route3Plan, state_ext: np.ndarray) -> np.ndarray:
    """state_ext: flat state with the identity row appended at
    plan.dead_slot's row.  Returns delivered values [R_out, 128]."""
    s2d = np.asarray(state_ext).reshape(-1, W)[:plan.n_state_rows]

    def layer(bind, sigma, pool):
        src = pool[bind]
        blk = np.take_along_axis(src, sigma, axis=1)
        n = blk.shape[0] // W
        return (blk.reshape(n, W, W).transpose(0, 2, 1)
                .reshape(-1, W))

    xT = (layer(plan.xbind, plan.sigma_x, s2d)
          if plan.xbind.size else np.zeros((0, W), s2d.dtype))
    pool1 = np.concatenate([s2d, xT], axis=0)
    wT = (layer(plan.wbind, plan.sigma_w, pool1)
          if plan.wbind.size else np.zeros((0, W), s2d.dtype))
    spill = np.asarray(state_ext)[plan.spill_need]
    pool = np.concatenate([s2d, xT, wT, spill], axis=0)
    zsrc = pool[plan.zbind]
    z = np.take_along_axis(zsrc, plan.sigma_z, axis=1)
    zT = (z.reshape(plan.n_blocks, W, W).transpose(0, 2, 1)
          .reshape(-1, W))
    return np.take_along_axis(zT, plan.sigma3, axis=1)
